"""End-to-end request tracing (r16): span trees from router to engine,
the step-timeline ring, trace_lint, and the metrics-registry audit.

The contracts this file pins (ISSUE r16 acceptance):

- with sample 1.0 a request yields ONE span tree covering
  queue -> admit -> prefill (chunks) -> decode steps -> complete that
  passes tools/trace_lint.py with ZERO leaked open spans;
- trace context survives the three stitch points — resurrection
  replay, keyed failover resubmission, deadline-expiry unwind — each
  producing a single well-formed tree;
- tracing off is the default and greedy outputs are BIT-IDENTICAL
  tracing on/off;
- the metrics registry obeys the exposition rules the PR 7 ``_total``
  collision taught: counter families end in _total, no
  counter/histogram family collisions, and prometheus_text() parses
  line-by-line.
"""

import importlib.util
import json
import os
import re
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed import fault_inject as fi
from paddle_tpu.inference import create_decode_engine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import ServingMetrics, SpanTracer
from paddle_tpu.serving.server import ServingServer, client_request
from paddle_tpu.serving.tracing import request_latencies

_LINT_PATH = os.path.join(os.path.dirname(__file__), "..", "tools",
                          "trace_lint.py")
_spec = importlib.util.spec_from_file_location("trace_lint", _LINT_PATH)
trace_lint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(trace_lint)


@pytest.fixture(autouse=True)
def _clean_injector():
    fi.reset()
    yield
    fi.reset()


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests (see
    conftest.module_compile_cache)."""
    yield


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


ENGINE_KW = dict(num_slots=2, page_size=8, max_seq_len=96, num_pages=24)


def _engine(m, **kw):
    merged = dict(ENGINE_KW)
    merged.update(kw)
    return create_decode_engine(m, **merged)


def _server(m, **kw):
    merged = dict(ENGINE_KW)
    merged.update(kw)
    merged.setdefault("metrics", ServingMetrics(registry=StatRegistry()))
    return ServingServer(m, **merged)


def _lint_ok(traces):
    errs = trace_lint.lint_trace_obj({"traces": traces})
    assert errs == [], errs


def _names(trace):
    return [s["name"] for s in trace["spans"]]


# ---------------------------------------------------------------------------
# SpanTracer unit semantics (no model)
# ---------------------------------------------------------------------------

class TestSpanTracerUnit:
    def test_sampling_is_deterministic(self):
        tr = SpanTracer(sample_rate=0.5)
        got = [tr.sample() for _ in range(8)]
        assert got == [False, True] * 4  # exactly every 2nd request
        assert not any(SpanTracer(sample_rate=0.0).sample()
                       for _ in range(10))
        assert all(SpanTracer(sample_rate=1.0).sample()
                   for _ in range(10))

    def test_start_unsampled_returns_none(self):
        tr = SpanTracer(sample_rate=0.0)
        assert tr.start("request") is None
        assert tr.sampled_total == 0

    def test_ctx_forces_sampling_and_records_remote_parent(self):
        tr = SpanTracer(sample_rate=0.0)
        t = tr.start("request", ctx={"id": "abc", "parent": "r:1"})
        assert t is not None and t.trace_id == "abc"
        tr.finish(t, state="done")
        root = tr.finished()[-1]["spans"][0]
        assert root["args"]["remote_parent"] == "r:1"
        assert root["parent"] is None  # locally orphan-free

    def test_span_cap_drops_and_counts(self):
        tr = SpanTracer(sample_rate=1.0, max_spans_per_trace=3)
        t = tr.start("request")
        for i in range(6):
            t.event(f"e{i}")
        tr.finish(t, state="done")
        d = tr.finished()[-1]
        assert len(d["spans"]) == 3
        assert d["dropped_spans"] == 4  # 4 of the 6 events dropped
        assert tr.spans_dropped_total == 4

    def test_finished_ring_is_bounded(self):
        tr = SpanTracer(sample_rate=1.0, max_traces=4)
        for _ in range(10):
            tr.finish(tr.start("request"), state="done")
        assert len(tr.finished()) == 4
        assert tr.finished_total == 10

    def test_finish_force_closes_and_counts_leaks(self):
        tr = SpanTracer(sample_rate=1.0)
        t = tr.start("request")
        t.begin("queue", parent=t.anchor)  # never closed
        tr.finish(t, state="done")
        d = tr.finished()[-1]
        assert d["leaked_open"] == 1
        assert all(s["t1_us"] is not None for s in d["spans"])
        # ...and trace_lint reports the leak
        errs = trace_lint.lint_trace_obj({"traces": [d]})
        assert errs and "force-closed" in errs[0]

    def test_chrome_export_shape(self):
        tr = SpanTracer(sample_rate=1.0)
        t = tr.start("request")
        sp = t.begin("queue", parent=t.anchor)
        t.end(sp)
        tr.finish(t, state="done")
        ch = tr.to_chrome()
        assert ch["traceEvents"]
        for e in ch["traceEvents"]:
            assert e["ph"] == "X" and e["dur"] >= 0 and e["ts"] >= 0
            assert e["args"]["trace_id"] == t.trace_id
        assert trace_lint.lint_trace_obj(ch) == []

    def test_sink_failure_never_breaks_tracing(self):
        def bad_sink(kind, tid, span):
            raise RuntimeError("boom")

        tr = SpanTracer(sample_rate=1.0, on_span=bad_sink)
        t = tr.start("request")
        t.event("x")
        tr.finish(t, state="done")
        assert tr.finished()


# ---------------------------------------------------------------------------
# trace_lint unit checks
# ---------------------------------------------------------------------------

class TestTraceLint:
    def _trace(self, spans, **kw):
        base = {"trace_id": "t", "pid": 1, "state": "done",
                "dropped_spans": 0, "leaked_open": 0, "spans": spans}
        base.update(kw)
        return base

    def test_valid_tree_passes(self):
        t = self._trace([
            {"sid": "a:1", "parent": None, "name": "request",
             "t0_us": 0.0, "t1_us": 100.0, "args": {}},
            {"sid": "a:2", "parent": "a:1", "name": "queue",
             "t0_us": 5.0, "t1_us": 50.0, "args": {}}])
        assert trace_lint.lint_trace_obj({"traces": [t]}) == []

    def test_orphan_parent_fails(self):
        t = self._trace([{"sid": "a:1", "parent": "ghost",
                          "name": "x", "t0_us": 0.0, "t1_us": 1.0,
                          "args": {}}])
        errs = trace_lint.lint_trace_obj({"traces": [t]})
        assert any("ORPHAN" in e for e in errs)

    def test_open_span_fails(self):
        t = self._trace([{"sid": "a:1", "parent": None, "name": "x",
                          "t0_us": 0.0, "t1_us": None, "args": {}}])
        errs = trace_lint.lint_trace_obj({"traces": [t]})
        assert any("OPEN" in e for e in errs)

    def test_reversed_timestamps_fail(self):
        t = self._trace([{"sid": "a:1", "parent": None, "name": "x",
                          "t0_us": 100.0, "t1_us": 10.0, "args": {}}])
        errs = trace_lint.lint_trace_obj({"traces": [t]})
        assert any("ends before" in e for e in errs)

    def test_child_escaping_parent_fails(self):
        t = self._trace([
            {"sid": "a:1", "parent": None, "name": "p",
             "t0_us": 0.0, "t1_us": 10.0, "args": {}},
            {"sid": "a:2", "parent": "a:1", "name": "c",
             "t0_us": 5.0, "t1_us": 50.0, "args": {}}])
        errs = trace_lint.lint_trace_obj({"traces": [t]})
        assert any("escapes parent" in e for e in errs)

    def test_duplicate_ids_fail(self):
        t = self._trace([
            {"sid": "a:1", "parent": None, "name": "x",
             "t0_us": 0.0, "t1_us": 1.0, "args": {}},
            {"sid": "a:1", "parent": None, "name": "y",
             "t0_us": 0.0, "t1_us": 1.0, "args": {}}])
        errs = trace_lint.lint_trace_obj({"traces": [t]})
        assert any("duplicate" in e for e in errs)

    def test_cli_roundtrip(self, tmp_path):
        import subprocess
        import sys
        good = tmp_path / "good.json"
        good.write_text(json.dumps({"traces": [self._trace([
            {"sid": "a:1", "parent": None, "name": "request",
             "t0_us": 0.0, "t1_us": 1.0, "args": {}}])]}))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traces": [self._trace([
            {"sid": "a:1", "parent": None, "name": "x",
             "t0_us": 0.0, "t1_us": None, "args": {}}])]}))
        assert subprocess.run(
            [sys.executable, _LINT_PATH, str(good)],
            capture_output=True).returncode == 0
        assert subprocess.run(
            [sys.executable, _LINT_PATH, str(bad)],
            capture_output=True).returncode == 1


# ---------------------------------------------------------------------------
# Engine tracing: span trees, timeline, costs, bit-identity
# ---------------------------------------------------------------------------

class TestEngineTracing:
    def test_whole_prefill_tree_shape(self, model):
        tr = SpanTracer(sample_rate=1.0)
        eng = _engine(model, tracer=tr)
        eng.submit(np.arange(1, 7, dtype=np.int32), 4)
        eng.run()
        eng.close()
        traces = tr.finished()
        assert len(traces) == 1
        t = traces[0]
        assert t["state"] == "done" and t["leaked_open"] == 0
        names = _names(t)
        for stage in ("request", "queue", "admit", "prefill",
                      "first_token", "decode", "decode_step",
                      "complete"):
            assert stage in names, names
        # lifecycle ordering: queue before admit before prefill ...
        assert names.index("queue") < names.index("admit") \
            < names.index("prefill") < names.index("first_token") \
            < names.index("complete")
        _lint_ok(traces)

    def test_chunked_prefill_tree_has_chunk_spans(self, model):
        tr = SpanTracer(sample_rate=1.0)
        eng = _engine(model, tracer=tr, prefill_chunk_tokens=8)
        eng.submit(np.arange(1, 20, dtype=np.int32), 4)
        eng.run()
        eng.close()
        t = tr.finished()[0]
        names = _names(t)
        # 19 tokens at chunk 8 -> 3 chunks
        assert names.count("prefill_chunk") == 3
        assert "decode_step" in names and t["leaked_open"] == 0
        # chunk spans nest under the open prefill stage span
        pref = next(s for s in t["spans"] if s["name"] == "prefill")
        for s in t["spans"]:
            if s["name"] == "prefill_chunk":
                assert s["parent"] == pref["sid"]
        _lint_ok([t])

    def test_speculative_tree_has_verify_steps(self, model):
        from paddle_tpu.inference import SpeculativeConfig
        tr = SpanTracer(sample_rate=1.0)
        eng = _engine(model, tracer=tr,
                      speculative=SpeculativeConfig(k=2, draft="ngram"))
        eng.submit(np.arange(1, 9, dtype=np.int32), 6)
        eng.run()
        eng.close()
        t = tr.finished()[0]
        names = _names(t)
        assert "verify_step" in names
        vs = next(s for s in t["spans"] if s["name"] == "verify_step")
        assert {"drafted", "accepted"} <= set(vs["args"])
        assert t["leaked_open"] == 0
        _lint_ok([t])

    def test_off_by_default_no_allocation(self, model):
        eng = _engine(model)
        rid = eng.submit(np.arange(1, 7, dtype=np.int32), 3)
        assert eng._queue[0].trace is None
        eng.run()
        eng.close()
        assert eng.result(rid) is None or True  # drained by run()

    def test_sample_rate_traces_every_other_request(self, model):
        tr = SpanTracer(sample_rate=0.5)
        eng = _engine(model, tracer=tr)
        for i in range(4):
            eng.submit(np.arange(1, 6, dtype=np.int32), 2)
        eng.run()
        eng.close()
        assert tr.sampled_total == 2
        assert len(tr.finished()) == 2

    def test_bit_identical_tracing_on_off(self, model):
        """The r16 pin: greedy outputs do not change with tracing."""
        prompts = [np.arange(1, 14, dtype=np.int32),
                   np.arange(3, 9, dtype=np.int32),
                   np.arange(5, 25, dtype=np.int32)]

        def run(tracer):
            eng = _engine(model, tracer=tracer,
                          prefill_chunk_tokens=8)
            rids = [eng.submit(p, 6) for p in prompts]
            out = eng.run()
            eng.close()
            return [[int(x) for x in out[r]] for r in rids]

        base = run(None)
        traced = run(SpanTracer(sample_rate=1.0))
        assert base == traced

    def test_request_latencies_from_trace(self, model):
        tr = SpanTracer(sample_rate=1.0)
        eng = _engine(model, tracer=tr)
        eng.submit(np.arange(1, 7, dtype=np.int32), 4)
        eng.run()
        eng.close()
        lat = request_latencies(tr.finished()[0])
        assert lat["tokens_out"] == 4
        assert lat["ttft_s"] is not None and lat["ttft_s"] >= 0
        assert lat["tpot_s"] is not None and lat["tpot_s"] >= 0
        assert lat["e2e_s"] >= lat["ttft_s"]

    def test_step_timeline_ring(self, model):
        eng = _engine(model, timeline_steps=4)
        for _ in range(3):
            eng.submit(np.arange(1, 7, dtype=np.int32), 6)
        eng.run()
        eng.close()
        tl = eng.step_timeline()
        assert 0 < len(tl) <= 4  # bounded ring
        last = tl[-1]
        for field in ("step", "ms", "programs", "slots_active",
                      "queued", "free_pages", "reserved_pages"):
            assert field in last, last
        assert any("decode_ms" in e for e in tl)
        assert eng.programs_launched.get("decode", 0) > 0

    def test_program_costs_captured_on_trace(self, model):
        eng = _engine(model, capture_costs=True)
        eng.submit(np.arange(1, 7, dtype=np.int32), 3)
        eng.run()
        eng.close()
        costs = eng.program_costs()
        assert "decode" in costs and "prefill" in costs
        assert costs["decode"].get("flops", 0) > 0
        assert costs["decode"].get("bytes_accessed", 0) > 0

    def test_costs_off_by_default(self, model):
        eng = _engine(model)
        eng.submit(np.arange(1, 7, dtype=np.int32), 2)
        eng.run()
        eng.close()
        assert eng.program_costs() == {}


# ---------------------------------------------------------------------------
# Stitch points: deadline unwind, resurrection replay, keyed failover
# ---------------------------------------------------------------------------

class TestStitchPoints:
    def test_deadline_expiry_in_queue_closes_tree(self, model):
        tr = SpanTracer(sample_rate=1.0)
        eng = _engine(model, tracer=tr)
        eng.submit(np.arange(1, 7, dtype=np.int32), 4,
                   deadline_t=time.monotonic() - 0.001)
        expired = eng.expire_deadlines()
        assert len(expired) == 1 and expired[0].state == "deadline"
        eng.close()
        t = tr.finished()[0]
        assert t["state"] == "deadline" and t["leaked_open"] == 0
        comp = next(s for s in t["spans"] if s["name"] == "complete")
        assert comp["args"]["state"] == "deadline"
        _lint_ok([t])

    def test_deadline_expiry_mid_decode_closes_tree(self, model):
        """Deterministic mid-decode expiry: run until the request is
        demonstrably decoding, then rewind its deadline — no wall-
        clock race against a loaded CI host's compile times."""
        tr = SpanTracer(sample_rate=1.0)
        eng = _engine(model, tracer=tr)
        eng.submit(np.arange(1, 7, dtype=np.int32), 64,
                   deadline_t=time.monotonic() + 300.0)
        for _ in range(3):  # admit + prefill + >=1 decode step
            eng.step()
        req = next(r for r in eng._slots if r is not None)
        assert req.state == "decoding"
        req.deadline_t = time.monotonic() - 1e-3
        eng.step()  # the expiry sweep evicts it typed
        assert eng.num_active == 0
        eng.close()
        t = tr.finished()[0]
        assert t["state"] == "deadline" and t["leaked_open"] == 0
        names = _names(t)
        assert "decode_step" in names  # it WAS decoding when evicted
        _lint_ok([t])

    def test_resurrection_replay_is_one_tree(self, model):
        """Engine death mid-decode: the replayed request's spans land
        on the ORIGINAL tree — one trace id, a resurrect_replay
        marker, a second queue/admit/prefill run, zero leaked spans."""
        fi.get_injector().arm("engine.step", at_calls=[3, 4])
        srv = _server(model, max_engine_errors=2, trace_sample=1.0)
        port = srv.start()
        rep = client_request(
            "127.0.0.1", port,
            {"op": "generate", "prompt": list(range(1, 7)),
             "max_new_tokens": 8})
        assert "error" not in rep, rep
        assert rep["stats"].get("replayed") is True
        tr = client_request("127.0.0.1", port, {"op": "trace"})
        traces = [t for t in tr["traces"] if t["state"] == "done"]
        assert len(traces) == 1  # ONE tree, not pre/post fragments
        t = traces[0]
        names = _names(t)
        assert "resurrect_replay" in names
        assert names.count("queue") == 2    # original + replay
        assert names.count("prefill") == 2  # original + chained replay
        assert names.count("complete") == 1
        assert t["leaked_open"] == 0
        _lint_ok([t])
        # latencies from the stitched tree describe the request the
        # CLIENT experienced: pre-crash tokens (resurrect_replay's
        # pre_tokens) + the replay slice — not just the final slice,
        # which would inflate the derived TPOT
        lat = request_latencies(t)
        assert lat["tokens_out"] == len(rep["generated"]) == 8
        # the tracer-level annotations carry the old debug vocabulary
        evs = [e["name"] for e in tr["events"]]
        assert "resurrect" in evs and "replay" in evs
        srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_keyed_failover_merges_into_one_tree(self, model):
        """Replica dies mid-stream -> keyed resubmission: the router's
        pick/forward/failover spans and the surviving replica's tree
        share one trace id and merge into a single lint-clean tree."""
        from paddle_tpu.serving.supervisor import FailoverRouter

        # replica 0: a protocol-speaking fake that dies mid-stream;
        # replica 1: a REAL traced server that serves the resubmission
        from test_crash_safe_serving import (_FakeReplicaServer,
                                             _FakeSupervisor)
        dying = _FakeReplicaServer(n_tokens=6, die_after=2)
        real = _server(model, trace_sample=0.0)  # ctx forces tracing
        real_port = real.start()
        sup = _FakeSupervisor([dying])
        rep1 = type("R", (), {})()
        rep1.idx, rep1.port, rep1.ready = 1, real_port, True
        rep1.restarts, rep1.alive = 0, lambda: True
        sup.replicas.append(rep1)
        router = FailoverRouter(sup, max_failover=3,
                                backend_timeout_s=30,
                                trace_sample=1.0)
        port = router.start()
        # round-robin: some requests land straight on the healthy
        # replica — drive until one actually failed over (its trace is
        # the one that must read as a single stitched tree)
        router_tree = None
        for attempt in range(6):
            got = client_request(
                "127.0.0.1", port,
                {"op": "generate", "prompt": [1, 2, 3],
                 "max_new_tokens": 6, "key": "k-trace",
                 "stream": True})
            assert "error" not in got, got
            rt = client_request("127.0.0.1", port, {"op": "trace"})
            cand = [t for t in rt["traces"]
                    if t["state"] == "done" and "failover" in _names(t)]
            if cand:
                router_tree = cand[-1]
                break
        assert router_tree is not None, "no failover trace produced"
        assert router.failovers_total >= 1
        names = _names(router_tree)
        assert names.count("forward") >= 2
        assert router_tree["leaked_open"] == 0
        # the REAL replica traced the resubmission under the router's
        # forward span (same trace id, remote_parent link)
        reps = client_request("127.0.0.1", real_port, {"op": "trace"})
        shared = [t for t in reps["traces"]
                  if t["trace_id"] == router_tree["trace_id"]]
        assert shared, (router_tree["trace_id"], reps["traces"])
        replica_tree = shared[-1]
        root = replica_tree["spans"][0]
        fwd_ids = {s["sid"] for s in router_tree["spans"]
                   if s["name"] == "forward"}
        assert root["args"]["remote_parent"] in fwd_ids
        # merged: rewrite the cross-process link and lint ONE tree
        merged_spans = [dict(s) for s in router_tree["spans"]]
        for s in replica_tree["spans"]:
            s = dict(s)
            if s["sid"] == root["sid"]:
                s["parent"] = root["args"]["remote_parent"]
            merged_spans.append(s)
        merged = {"trace_id": router_tree["trace_id"], "pid": -1,
                  "state": "done", "dropped_spans": 0,
                  "leaked_open": 0, "spans": merged_spans}
        # containment across participants is only checked same-pid;
        # here both live in THIS process, and the replica's share sits
        # inside the successful forward span by construction
        _lint_ok([merged])
        router.stop()
        real.stop()
        dying.close()

    def test_loopback_server_trace_passes_lint(self, model):
        """The r16 acceptance loopback: --trace-sample 1.0, one
        request, tree covers queue->admit->chunks->decode->complete
        and the DUMPED FILE passes tools/trace_lint.py."""
        import subprocess
        import sys
        srv = _server(model, trace_sample=1.0, prefill_chunk_tokens=8)
        port = srv.start()
        rep = client_request(
            "127.0.0.1", port,
            {"op": "generate", "prompt": list(range(1, 20)),
             "max_new_tokens": 4})
        assert "error" not in rep, rep
        tr = client_request("127.0.0.1", port, {"op": "trace"})
        assert tr["step_timeline"], "timeline missing from trace op"
        names = _names(tr["traces"][0])
        for stage in ("queue", "admit", "prefill_chunk", "decode_step",
                      "complete"):
            assert stage in names, names
        import tempfile
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as f:
            json.dump({"traces": tr["traces"]}, f)
            path = f.name
        try:
            r = subprocess.run([sys.executable, _LINT_PATH, path],
                               capture_output=True, text=True)
            assert r.returncode == 0, r.stderr
        finally:
            os.unlink(path)
        srv.stop()


# ---------------------------------------------------------------------------
# Server observability surface: gauges, costs, debug env
# ---------------------------------------------------------------------------

class TestServerSurface:
    def test_trace_op_chrome_and_merge(self, model, tmp_path):
        srv = _server(model, trace_sample=1.0)
        port = srv.start()
        rep = client_request(
            "127.0.0.1", port,
            {"op": "generate", "prompt": [1, 2, 3],
             "max_new_tokens": 3})
        assert "error" not in rep
        ch = client_request("127.0.0.1", port,
                            {"op": "trace", "format": "chrome"})
        assert ch["chrome"]["traceEvents"]
        assert trace_lint.lint_trace_obj(ch["chrome"]) == []
        # merges with another chrome trace via tools/merge_traces.py
        spec = importlib.util.spec_from_file_location(
            "merge_traces", os.path.join(os.path.dirname(_LINT_PATH),
                                         "merge_traces.py"))
        mt = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mt)
        a = tmp_path / "serving.json"
        a.write_text(json.dumps(ch["chrome"]))
        b = tmp_path / "device.json"
        b.write_text(json.dumps({"traceEvents": [
            {"name": "xla_op", "ph": "X", "ts": 1.0, "dur": 2.0,
             "pid": 0, "tid": 0}]}))
        merged = mt.merge([str(a), str(b)])
        assert any(e.get("name") == "xla_op" for e in merged)
        assert any(e.get("name") == "complete" for e in merged)
        srv.stop()

    def test_gauges_carry_costs_timeline_and_traces(self, model):
        srv = _server(model, trace_sample=1.0)
        port = srv.start()
        client_request("127.0.0.1", port,
                       {"op": "generate", "prompt": [1, 2, 3],
                        "max_new_tokens": 3})
        g = srv._gauges()
        assert g.get("programs_launched_decode", 0) > 0
        assert g.get("program_decode_flops", 0) > 0
        assert g.get("program_decode_bytes_accessed", 0) > 0
        assert g.get("engine_steps", 0) > 0
        assert "step_last_ms" in g
        # scrape-time counter sync from the tracer
        assert srv.metrics.counter("traces_sampled_total").get() >= 1
        assert srv.metrics.counter("traces_finished_total").get() >= 1
        # the step histogram got fed from ring deltas
        assert srv.metrics.step_ms.total > 0
        st = client_request("127.0.0.1", port, {"op": "stats"})
        assert st["stats"]["step_ms"]["count"] > 0
        srv.stop()

    def test_debug_env_is_tracer_with_stderr_sink(self, model,
                                                  monkeypatch, capfd):
        monkeypatch.setenv("PT_SERVING_DEBUG", "1")
        srv = _server(model)
        assert srv.tracer.sample_rate == 1.0
        port = srv.start()
        rep = client_request("127.0.0.1", port,
                             {"op": "generate", "prompt": [1, 2, 3],
                              "max_new_tokens": 2})
        assert "error" not in rep
        srv.stop()
        err = capfd.readouterr().err
        assert "[pt-serving-trace" in err
        assert "complete" in err  # lifecycle event vocabulary

    def test_health_reports_trace_sample(self, model):
        srv = _server(model, trace_sample=0.25)
        port = srv.start()
        h = client_request("127.0.0.1", port, {"op": "health"})
        assert h["trace_sample"] == 0.25
        srv.stop()


# ---------------------------------------------------------------------------
# Metrics-registry audit (satellite: the PR 7 _total collision lesson)
# ---------------------------------------------------------------------------

_PROM_TYPE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram)$")
_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? "
    r"(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|NaN|[+-]Inf)$")


class TestMetricsRegistryAudit:
    def _families(self, text):
        fams = {}
        for line in text.splitlines():
            m = _PROM_TYPE.match(line)
            if m:
                fams[m.group(1)] = m.group(2)
        return fams

    def test_every_counter_family_ends_in_total(self):
        for name in ServingMetrics.COUNTERS:
            assert name.endswith("_total"), (
                f"counter family {name!r} must end in _total "
                f"(OpenMetrics counter convention)")

    def test_no_counter_histogram_family_collisions(self, model):
        srv = _server(model, trace_sample=1.0)
        port = srv.start()
        client_request("127.0.0.1", port,
                       {"op": "generate", "prompt": [1, 2, 3],
                        "max_new_tokens": 2})
        text = client_request("127.0.0.1", port,
                              {"op": "metrics"})["text"]
        srv.stop()
        fams = self._families(text)
        hist = {n for n, t in fams.items() if t == "histogram"}
        counters = {n for n, t in fams.items() if t == "counter"}
        gauges = {n for n, t in fams.items() if t == "gauge"}
        assert fams, "no TYPE lines in exposition"
        # family names unique across types by construction of the dict
        # — check the IMPLICIT names too: a histogram family F owns
        # F_bucket/F_sum/F_count, a counter family ends _total and its
        # base must not be a histogram family (the PR 7 near-miss)
        for c in counters:
            assert c.endswith("_total"), c
            base = c[:-len("_total")]
            assert base not in hist, (
                f"counter {c} collides with histogram family {base}")
            assert base not in gauges or True  # gauge/counter disjoint
        for h in hist:
            assert not h.endswith("_total"), (
                f"histogram family {h} must not use the reserved "
                f"_total suffix")
            for suffix in ("_bucket", "_sum", "_count"):
                assert h + suffix not in counters | gauges | hist

    def test_prometheus_text_parses_line_by_line(self, model):
        srv = _server(model, trace_sample=1.0)
        port = srv.start()
        client_request("127.0.0.1", port,
                       {"op": "generate", "prompt": [1, 2, 3],
                        "max_new_tokens": 2})
        text = client_request("127.0.0.1", port,
                              {"op": "metrics"})["text"]
        srv.stop()
        assert text.endswith("\n")
        for line in text.splitlines():
            if not line:
                continue
            assert _PROM_TYPE.match(line) or _PROM_SAMPLE.match(line), (
                f"line does not parse against the exposition "
                f"format: {line!r}")

    def test_declared_counters_exported_at_zero(self):
        met = ServingMetrics(registry=StatRegistry())
        text = met.prometheus_text()
        for name in ("traces_sampled_total", "traces_finished_total",
                     "trace_spans_dropped_total"):
            assert f"serving_{name} 0" in text

    def test_r18_memory_families_ride_the_audit(self, model):
        """r18 extension: the memory observatory's new families — the
        serving_request_peak_pages histogram and the occupancy/ledger
        gauges — appear on the exposition page with the right types
        (the generic collision/parse audits above already cover them
        by running over the same page)."""
        srv = _server(model)
        port = srv.start()
        client_request("127.0.0.1", port,
                       {"op": "generate", "prompt": [1, 2, 3],
                        "max_new_tokens": 2})
        text = client_request("127.0.0.1", port,
                              {"op": "metrics"})["text"]
        srv.stop()
        fams = self._families(text)
        assert fams.get("serving_request_peak_pages") == "histogram"
        for g in ("serving_pages_inflight",
                  "serving_pages_prefix_device", "serving_pages_used",
                  "serving_ledger_events"):
            assert fams.get(g) == "gauge", (g, fams.get(g))

    def test_fleet_exposition_obeys_the_same_rules(self):
        """r17 extension: the FLEET exposition (per-replica series
        with a replica label + fleet_* rollup families) must obey the
        exact audit this class pins for one replica — counter
        families end _total, no histogram/counter family collisions
        (rollups live in distinct fleet_* families, so an unlabeled
        rollup can never collide with a labeled series), every line
        parses."""
        from paddle_tpu.serving.fleet_metrics import FleetMetrics
        fm = FleetMetrics()
        for i in range(2):
            met = ServingMetrics(registry=StatRegistry())
            met.ttft_ms.observe(2.0 + i)
            met.counter("requests_total").add()
            fm.ingest(i, met.export())
        text = fm.prometheus_text()
        assert text.endswith("\n")
        fams = self._families(text)
        assert fams, "no TYPE lines in fleet exposition"
        hist = {n for n, t in fams.items() if t == "histogram"}
        counters = {n for n, t in fams.items() if t == "counter"}
        gauges = {n for n, t in fams.items() if t == "gauge"}
        for c in counters:
            assert c.endswith("_total"), c
            assert c[:-len("_total")] not in hist, c
        for h in hist:
            assert not h.endswith("_total"), h
            for suffix in ("_bucket", "_sum", "_count"):
                assert h + suffix not in counters | gauges | hist
        # replica-labeled series and fleet rollups never share a family
        assert not {f for f in fams if f.startswith("serving_")} & \
            {f for f in fams if f.startswith("fleet_")}
        for line in text.splitlines():
            if not line:
                continue
            assert _PROM_TYPE.match(line) or _PROM_SAMPLE.match(line), (
                f"fleet exposition line does not parse: {line!r}")
