"""Native lib, PyLayer, control flow, launcher/elastic, profiler tests."""

import multiprocessing
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn


# -- native -------------------------------------------------------------------

def test_native_builds():
    from paddle_tpu import native
    assert native.available(), "g++ build of ptnative failed"


def test_crc32c():
    from paddle_tpu import native
    # known crc32c vector: "123456789" -> 0xE3069283
    if native.get_lib() is not None:
        assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(b"abc") == native.crc32c(b"abc")
    assert native.crc32c(b"abc") != native.crc32c(b"abd")


def test_u8_norm_matches_numpy():
    from paddle_tpu import native
    img = np.random.default_rng(0).integers(0, 256, (3, 8, 8)).astype(
        np.uint8)
    mean = [0.485, 0.456, 0.406]
    std = [0.229, 0.224, 0.225]
    got = native.u8_to_f32_norm(img, mean, std)
    expect = (img.astype(np.float32) / 255.0 -
              np.asarray(mean, np.float32).reshape(3, 1, 1)) / \
        np.asarray(std, np.float32).reshape(3, 1, 1)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def _producer(qname, n):
    from paddle_tpu import native
    q = native.ShmQueue(qname, create=False)
    for i in range(n):
        q.push_array(np.full((64,), i, np.float32))


def test_shm_queue_roundtrip():
    from paddle_tpu import native
    if not native.available():
        pytest.skip("native lib unavailable")
    qname = f"test_{os.getpid()}"
    q = native.ShmQueue(qname, slot_size=1 << 12, n_slots=4)
    try:
        ctx = multiprocessing.get_context("fork")
        p = ctx.Process(target=_producer, args=(qname, 10))
        p.start()
        got = []
        for _ in range(10):
            data = q.pop()
            got.append(np.frombuffer(data, np.float32)[0])
        p.join(timeout=10)
        assert sorted(got) == list(range(10))
    finally:
        q.destroy()


# -- PyLayer ------------------------------------------------------------------

def test_pylayer_custom_backward():
    from paddle_tpu.autograd.py_layer import PyLayer

    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor()
            return g * 3.0 * x * x

    x = pt.to_tensor([2.0], stop_gradient=False)
    y = Cube.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_pylayer_scaled_backward():
    from paddle_tpu.autograd.py_layer import PyLayer

    class TimesTwoGradTen(PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2.0

        @staticmethod
        def backward(ctx, g):
            return g * 10.0

    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    TimesTwoGradTen.apply(x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [10.0, 10.0])


# -- control flow ---------------------------------------------------------

def test_cond_and_while_loop():
    from paddle_tpu.ops.control_flow import cond, while_loop

    x = pt.to_tensor([3.0])
    out = cond(pt.to_tensor(True), lambda v: v * 2, lambda v: v * 10, x)
    np.testing.assert_allclose(out.numpy(), [6.0])

    i = pt.to_tensor(0)
    acc = pt.to_tensor(0.0)
    i_f, acc_f = while_loop(lambda i_, a: i_ < 5,
                            lambda i_, a: (i_ + 1, a + 2.0), (i, acc))
    assert int(i_f.numpy()) == 5
    np.testing.assert_allclose(acc_f.numpy(), 10.0)


def test_switch_case_and_scan():
    from paddle_tpu.ops.control_flow import scan, switch_case

    out = switch_case(pt.to_tensor(1),
                      [lambda: pt.to_tensor([1.0]),
                       lambda: pt.to_tensor([2.0]),
                       lambda: pt.to_tensor([3.0])])
    np.testing.assert_allclose(out.numpy(), [2.0])

    xs = pt.to_tensor(np.arange(5, dtype=np.float32))
    carry, ys = scan(lambda c, x: (c + x, c + x), pt.to_tensor(0.0), xs)
    np.testing.assert_allclose(carry.numpy(), 10.0)
    np.testing.assert_allclose(ys.numpy(), [0, 1, 3, 6, 10])


def test_control_flow_inside_jit():
    import jax
    from paddle_tpu.ops.control_flow import while_loop

    def f(n):
        i, s = while_loop(lambda i_, s_: i_ < n,
                          lambda i_, s_: (i_ + 1, s_ + i_),
                          (pt.to_tensor(0), pt.to_tensor(0)))
        return s.value

    out = jax.jit(f)(5)
    assert int(out) == 10


# -- launcher / elastic ---------------------------------------------------

def test_launcher_runs_multiproc():
    from paddle_tpu.distributed.launch import launch_procs, watch_procs

    with tempfile.TemporaryDirectory() as d:
        script = os.path.join(d, "worker.py")
        with open(script, "w") as f:
            f.write(
                "import os\n"
                "print('rank', os.environ['PT_PROCESS_ID'], 'of',\n"
                "      os.environ['PT_NUM_PROCESSES'])\n")
        procs = launch_procs([script], nproc=2,
                             coordinator="127.0.0.1:29500", log_dir=d)
        code = watch_procs(procs, poll_s=0.2)
        assert code == 0
        log0 = open(os.path.join(d, "workerlog.0")).read()
        assert "rank 0 of 2" in log0


def test_launcher_propagates_failure():
    from paddle_tpu.distributed.launch import launch_procs, watch_procs

    with tempfile.TemporaryDirectory() as d:
        script = os.path.join(d, "bad.py")
        with open(script, "w") as f:
            f.write("import os, sys\n"
                    "sys.exit(3 if os.environ['PT_PROCESS_ID']=='1' "
                    "else 0)\n")
        procs = launch_procs([script], nproc=2,
                             coordinator="127.0.0.1:29501", log_dir=d)
        code = watch_procs(procs, poll_s=0.2)
        assert code == 3


def test_elastic_membership():
    from paddle_tpu.distributed.elastic import (ElasticManager,
                                                FileMembershipStore)

    with tempfile.TemporaryDirectory() as d:
        store = FileMembershipStore(d, ttl_s=5.0)
        changes = []
        m0 = ElasticManager("job1", 0, 2, store,
                            on_change=lambda mem: changes.append(len(mem)),
                            heartbeat_s=0.1)
        m1 = ElasticManager("job1", 1, 2, store, heartbeat_s=0.1)
        m0.start()
        m1.start()

        # poll with a deadline instead of one fixed sleep: on a loaded
        # 2-cpu host the 0.1 s heartbeat threads can miss a 0.5 s
        # window (observed flaking under a concurrent test lane); the
        # semantics under test are reach-healthy / notice-scale-down,
        # not heartbeat latency
        def wait_for(cond, timeout_s=10.0):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                if cond():
                    return True
                time.sleep(0.1)
            return cond()

        assert wait_for(m0.healthy)
        # the watch thread must have taken its FIRST observation (the
        # change-detection baseline) before the scale-down happens: on
        # a 1-cpu host the main thread otherwise reaches stop() before
        # the watch loop ever runs, the baseline is post-scale-down,
        # and on_change can never fire (observed deterministic there)
        assert wait_for(lambda: m0._last_members is not None)
        m1.stop()  # scale-down event
        assert wait_for(lambda: not m0.healthy())
        # the watch-loop callback runs on its own cadence — poll it too
        assert wait_for(lambda: bool(changes)), \
            "membership change not observed"
        m0.stop()


# -- profiler ----------------------------------------------------------------

def test_profiler_records_and_exports():
    import json
    from paddle_tpu.core import (RecordEvent, disable_profiler,
                                 enable_profiler, export_chrome_trace)
    from paddle_tpu.core.profiler import profiler_events

    enable_profiler()
    with RecordEvent("my_region"):
        pt.matmul(pt.randn((8, 8)), pt.randn((8, 8)))
    disable_profiler()
    events = profiler_events()
    assert any(e.name == "my_region" for e in events)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "trace.json")
        export_chrome_trace(path)
        trace = json.load(open(path))
        assert any(ev["name"] == "my_region"
                   for ev in trace["traceEvents"])


def test_benchmark_flag_collects_stats():
    from paddle_tpu.core import GLOBAL_STATS, set_flags

    set_flags({"benchmark": True})
    try:
        pt.add(pt.ones((4,)), pt.ones((4,)))
    finally:
        set_flags({"benchmark": False})
    snap = GLOBAL_STATS.snapshot()
    assert any(k.startswith("op_us/add") for k in snap)


def test_tcp_membership_store():
    """Network membership registry (cross-host, NO shared filesystem):
    same ElasticManager semantics over the TCP store."""
    from paddle_tpu.distributed.elastic import (ElasticManager,
                                                MembershipServer,
                                                TcpMembershipStore)

    srv = MembershipServer(host="127.0.0.1", ttl_s=5.0)
    try:
        ep = f"127.0.0.1:{srv.port}"
        s0 = TcpMembershipStore(ep)
        s1 = TcpMembershipStore(ep)  # independent client, own connection
        changes = []
        m0 = ElasticManager("jobT", 0, 2, s0,
                            on_change=lambda mem: changes.append(len(mem)),
                            heartbeat_s=0.1)
        m1 = ElasticManager("jobT", 1, 2, s1, heartbeat_s=0.1)
        m0.start()
        m1.start()
        time.sleep(0.5)
        assert m0.healthy()
        assert s0.members("jobT")[1]["host"]
        m1.stop()  # deregisters over the wire
        time.sleep(0.5)
        assert not m0.healthy()
        assert changes, "membership change not observed"
        m0.stop()
    finally:
        srv.close()


def test_tcp_membership_ttl_prunes_dead_rank():
    from paddle_tpu.distributed.elastic import (MembershipServer,
                                                TcpMembershipStore)

    srv = MembershipServer(host="127.0.0.1", ttl_s=0.3)
    try:
        st = TcpMembershipStore(f"127.0.0.1:{srv.port}")
        st.register("jobD", 0, {})
        st.register("jobD", 1, {})
        assert sorted(st.members("jobD")) == [0, 1]
        deadline = time.time() + 3.0
        while time.time() < deadline:
            st.heartbeat("jobD", 0)  # rank 1 went silent (killed)
            if sorted(st.members("jobD")) == [0]:
                break
            time.sleep(0.1)
        assert sorted(st.members("jobD")) == [0]
    finally:
        srv.close()


def test_launcher_serves_membership_registry():
    """--membership serve: the launcher hosts the TCP registry and
    exports PT_MEMBER_EP; workers register over the wire only."""
    from paddle_tpu.distributed.launch import main as launch_main

    with tempfile.TemporaryDirectory() as d:
        script = os.path.join(d, "worker.py")
        with open(script, "w") as f:
            f.write(
                "import os, sys\n"
                f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
                "from paddle_tpu.distributed.elastic import "
                "TcpMembershipStore\n"
                "st = TcpMembershipStore(os.environ['PT_MEMBER_EP'])\n"
                "rank = int(os.environ['PT_PROCESS_ID'])\n"
                "st.register('jobL', rank, {})\n"
                "assert rank in st.members('jobL')\n")
        code = launch_main(["--nproc", "2", "--coordinator",
                            "127.0.0.1:29502", "--log_dir", d,
                            "--membership", "serve", script])
        assert code == 0, open(os.path.join(d, "workerlog.0")).read()
