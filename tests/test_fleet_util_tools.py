"""fleet.util + tools/ benchmark harness.

Reference parity: distributed/fleet/base/util_factory.py tests and the
tools/check_op_benchmark_result.py CI gate."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt

pytestmark = pytest.mark.slow  # covered breadth; fast lane keeps sibling smokes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fleet_util_single_process():
    u = pt.distributed.fleet.util
    assert u.all_gather(7) == [7]
    assert u.all_reduce(np.array([1.0, 2.0])).tolist() == [1.0, 2.0]
    assert u.all_reduce(5, mode="max") == 5
    u.barrier()  # no-op single process
    with pytest.raises(TypeError):
        u.get_file_shard("not-a-list")


def test_get_file_shard_blocked_split():
    from paddle_tpu.distributed.fleet_util import _blocked_range

    # 7 files over 3 ranks: 3/2/2, disjoint + covering, reference split
    spans = [_blocked_range(7, r, 3) for r in range(3)]
    assert spans == [(0, 3), (3, 5), (5, 7)]
    spans = [_blocked_range(4, r, 4) for r in range(4)]
    assert spans == [(0, 1), (1, 2), (2, 3), (3, 4)]
    # more ranks than files: tail ranks get nothing
    spans = [_blocked_range(2, r, 4) for r in range(4)]
    assert spans == [(0, 1), (1, 2), (2, 2), (2, 2)]


def test_op_benchmark_and_checker(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base = tmp_path / "base"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "op_benchmark.py"),
         "--ops", "add,softmax", "--repeat", "3",
         "--output", str(base)],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stderr
    lines = [json.loads(l) for l in out.stdout.splitlines() if l.strip()]
    assert {r["case"] for r in lines} == {"add", "softmax"}
    assert all(r["avg_us"] > 0 for r in lines)

    # identical logs pass the gate
    ck = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "check_op_benchmark_result.py"),
         "--develop_logs_dir", str(base), "--pr_logs_dir", str(base)],
        capture_output=True, text=True, timeout=60)
    assert ck.returncode == 0, ck.stdout + ck.stderr

    # a fabricated 10x regression fails with exit code 8
    slow = tmp_path / "slow"
    os.makedirs(slow)
    for fn in os.listdir(base):
        rec = json.loads(open(base / fn).read())
        rec["avg_us"] *= 10
        with open(slow / fn, "w") as f:
            f.write(json.dumps(rec) + "\n")
    ck = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "check_op_benchmark_result.py"),
         "--develop_logs_dir", str(base), "--pr_logs_dir", str(slow)],
        capture_output=True, text=True, timeout=60)
    assert ck.returncode == 8
    assert "REGRESSED" in ck.stdout


def test_unknown_op_rejected():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "op_benchmark.py"),
         "--ops", "definitely_not_an_op"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    assert out.returncode == 2


def test_merge_traces(tmp_path):
    t0 = {"traceEvents": [
        {"name": "step", "ph": "X", "ts": 1000, "dur": 5, "pid": 1,
         "tid": 1}]}
    t1 = [{"name": "allreduce", "ph": "X", "ts": 2000, "dur": 3,
           "pid": 1, "tid": 1}]
    p0, p1 = tmp_path / "host0.json", tmp_path / "host1.json"
    p0.write_text(json.dumps(t0))
    p1.write_text(json.dumps(t1))
    out = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "merge_traces.py"),
         "--out", str(out), str(p0), str(p1)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    merged = json.loads(out.read_text())["traceEvents"]
    pids = {e["pid"] for e in merged if e.get("ph") == "X"}
    assert pids == {"host0/1", "host1/1"}
    # per-source start alignment
    assert all(e["ts"] == 0 for e in merged if e.get("ph") == "X")


def test_flops_and_summary():
    import paddle_tpu as pt
    from paddle_tpu import nn

    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    f = pt.flops(net, (2, 32))
    ref = 2 * 2 * (32 * 64 + 64 * 8)  # 2 * batch * madds
    assert ref * 0.5 <= f <= ref * 2.5, (f, ref)
    info = pt.summary(net)
    assert info["total_params"] == 32 * 64 + 64 + 64 * 8 + 8
