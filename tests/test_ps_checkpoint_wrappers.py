"""PS mode, distributed checkpoint, optimizer wrappers, BERT tests."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as optim

pytestmark = pytest.mark.slow  # covered breadth; fast lane keeps sibling smokes


# -- parameter server ---------------------------------------------------------

def test_ps_dense_pull_push():
    from paddle_tpu.distributed.ps import PSClient, PSServer

    srv = PSServer()
    srv.add_dense_table("w", (4,), optimizer="sgd", lr=0.1)
    srv.start()
    try:
        client = PSClient([srv.endpoint])
        client.push_dense_init("w", np.ones(4, np.float32))
        np.testing.assert_allclose(client.pull_dense("w"), np.ones(4))
        client.push_dense_grad("w", np.full(4, 2.0, np.float32))
        np.testing.assert_allclose(client.pull_dense("w"),
                                   np.full(4, 0.8), rtol=1e-6)
        client.stop()
    finally:
        srv.stop()


def test_ps_sparse_sharded_across_servers():
    from paddle_tpu.distributed.ps import PSClient, PSServer

    servers = [PSServer(), PSServer()]
    for s in servers:
        s.add_sparse_table("emb", emb_dim=8, lr=0.5, optimizer="sgd")
        s.start()
    try:
        client = PSClient([s.endpoint for s in servers])
        keys = np.array([0, 1, 2, 3, 10, 11])
        rows = client.pull_sparse("emb", keys)
        assert rows.shape == (6, 8)
        # push grads and verify rows move
        grads = np.ones((6, 8), np.float32)
        client.push_sparse_grad("emb", keys, grads)
        rows2 = client.pull_sparse("emb", keys)
        np.testing.assert_allclose(rows2, rows - 0.5, rtol=1e-5)
        # rows landed on both servers
        assert servers[0].sparse["emb"].size() > 0
        assert servers[1].sparse["emb"].size() > 0
        client.stop()
    finally:
        for s in servers:
            s.stop()


def test_ps_async_communicator():
    from paddle_tpu.distributed.ps import (AsyncCommunicator, PSClient,
                                           PSServer)

    srv = PSServer()
    srv.add_dense_table("w", (2,), lr=1.0)
    srv.start()
    try:
        client = PSClient([srv.endpoint])
        client.push_dense_init("w", np.zeros(2, np.float32))
        comm = AsyncCommunicator(client, send_wait_s=0.01)
        comm.start()
        for _ in range(5):
            comm.push("w", np.ones(2, np.float32))
        comm.stop()
        np.testing.assert_allclose(client.pull_dense("w"),
                                   np.full(2, -5.0), rtol=1e-6)
        client.stop()
    finally:
        srv.stop()


def test_ps_end_to_end_training():
    """Sparse embedding regression trained via PS pull/push converges."""
    from paddle_tpu.distributed.ps import PSClient, PSServer

    srv = PSServer()
    srv.add_sparse_table("emb", emb_dim=4, lr=0.3, optimizer="sgd",
                         initializer_std=0.1)
    srv.start()
    try:
        client = PSClient([srv.endpoint])
        rng = np.random.default_rng(0)
        target = rng.standard_normal((8, 4)).astype(np.float32)
        for _ in range(60):
            keys = rng.integers(0, 8, 16)
            rows = client.pull_sparse("emb", keys)
            grad = 2 * (rows - target[keys])  # d/dr ||r - t||^2
            client.push_sparse_grad("emb", keys, grad)
        final = client.pull_sparse("emb", np.arange(8))
        assert np.abs(final - target).mean() < 0.1
        client.stop()
    finally:
        srv.stop()


# -- distributed checkpoint ---------------------------------------------------

def test_orbax_checkpoint_roundtrip():
    import jax.numpy as jnp
    from paddle_tpu.distributed.checkpoint import (load_sharded,
                                                   save_sharded)

    state = {"w": jnp.arange(8.0), "nested": {"b": jnp.ones((2, 2))}}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt")
        save_sharded(state, path)
        restored = load_sharded(path)
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.arange(8.0))
        np.testing.assert_allclose(np.asarray(restored["nested"]["b"]),
                                   np.ones((2, 2)))


def test_checkpoint_manager_trainstep_resume():
    from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                                   restore_train_state,
                                                   save_train_state)
    from paddle_tpu.jit import TrainStep

    X = np.random.default_rng(0).standard_normal((16, 4)).astype(np.float32)
    Y = np.random.default_rng(1).standard_normal((16, 1)).astype(np.float32)
    mse = nn.MSELoss()

    pt.seed(0)
    net = nn.Linear(4, 1)
    step = TrainStep(net, optim.Adam(learning_rate=0.05),
                     lambda m, b: mse(m(b[0]), b[1]))
    for _ in range(3):
        step((X, Y))
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, max_to_keep=2, use_async=False)
        save_train_state(step, None, step=3, manager=mgr)
        mgr.wait_until_finished()
        # train further, then restore back to step 3
        loss_at_3 = float(step((X, Y)))
        restore_train_state(step, manager=mgr, step=3)
        loss_resumed = float(step((X, Y)))
        np.testing.assert_allclose(loss_resumed, loss_at_3, rtol=1e-5)
        assert mgr.latest_step() == 3
        mgr.close()


# -- optimizer wrappers -------------------------------------------------------

def test_ema():
    from paddle_tpu.optimizer.wrappers import ExponentialMovingAverage

    p = pt.Parameter(np.array([0.0], np.float32))
    ema = ExponentialMovingAverage([p], decay=0.5)
    p.value = p.value + 1.0
    ema.update()
    p.value = p.value + 1.0
    ema.update()
    with ema.apply_guard():
        shadowed = float(p.numpy()[0])
    assert 0.0 < shadowed < 2.0
    assert float(p.numpy()[0]) == 2.0  # restored


def test_lookahead():
    from paddle_tpu.optimizer.wrappers import Lookahead

    w = pt.Parameter(np.array([4.0], np.float32))
    inner = optim.SGD(learning_rate=0.1, parameters=[w])
    look = Lookahead(inner, alpha=0.5, k=2)
    for _ in range(4):
        (w * w).sum().backward()
        look.step()
        look.clear_grad()
    assert abs(float(w.numpy()[0])) < 4.0


def test_model_average():
    from paddle_tpu.optimizer.wrappers import ModelAverage

    p = pt.Parameter(np.array([0.0], np.float32))
    ma = ModelAverage(parameters=[p], min_average_window=10,
                      max_average_window=100)
    for v in [1.0, 2.0, 3.0]:
        p.value = np.array([v], np.float32)
        ma.step()
    with ma.apply_guard():
        np.testing.assert_allclose(p.numpy(), [2.0], rtol=1e-6)


# -- BERT ---------------------------------------------------------------------

def test_bert_forward_and_loss():
    from paddle_tpu.models.bert import (BertForPretraining,
                                        BertForSequenceClassification,
                                        bert_tiny)

    cfg = bert_tiny()
    ids = pt.to_tensor((np.arange(2 * 16) % 100).reshape(2, 16))
    model = BertForPretraining(cfg)
    labels = pt.to_tensor((np.arange(2 * 16) % 100).reshape(2, 16))
    nsp = pt.to_tensor(np.array([0, 1]))
    loss = model(ids, labels=labels, next_sentence_labels=nsp)
    assert np.isfinite(float(loss.numpy()))
    loss.backward()
    assert model.bert.embeddings.word_embeddings.weight.grad is not None

    clf = BertForSequenceClassification(cfg, num_classes=3)
    logits = clf(ids)
    assert logits.shape == (2, 3)


def test_bert_attention_mask():
    from paddle_tpu.models.bert import BertModel, bert_tiny

    cfg = bert_tiny()
    model = BertModel(cfg)
    model.eval()
    ids = pt.to_tensor((np.arange(2 * 8) % 100).reshape(2, 8))
    mask = pt.to_tensor(np.array([[1] * 8, [1] * 4 + [0] * 4]))
    seq, pooled = model(ids, attention_mask=mask)
    assert seq.shape == (2, 8, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)
