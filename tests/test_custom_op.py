"""Custom-op C ABI (PD_BUILD_OP analog): compile a real C++ kernel with
g++, load via ctypes, run inside jit via pure_callback, grad via the C
backward symbol. Reference: extension/ext_op_meta_info.h + cpp_extension."""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.utils import cpp_extension

_SRC = textwrap.dedent("""
    #include "pt_custom_op.h"
    #include <cmath>

    // relu2(x) = max(x, 0)^2 — forward, infer, and backward
    PT_BUILD_OP(relu2) {
      if (n_in != 1 || n_out != 1) return 1;
      const float* x = (const float*)ins[0].data;
      float* y = (float*)outs[0].data;
      int64_t n = ptop_numel(&ins[0]);
      for (int64_t i = 0; i < n; ++i) {
        float v = x[i] > 0.f ? x[i] : 0.f;
        y[i] = v * v;
      }
      return 0;
    }

    PT_BUILD_INFER(relu2) {
      if (n_in != 1 || n_out != 1) return 1;
      out_ndims[0] = in_ndims[0];
      out_dtypes[0] = in_dtypes[0];
      for (int i = 0; i < in_ndims[0]; ++i) out_dims[i] = in_dims[i];
      return 0;
    }

    // ins = [x, y, dy] -> outs = [dx]; d/dx relu2 = 2x for x>0
    PT_BUILD_GRAD_OP(relu2) {
      if (n_in != 3 || n_out != 1) return 1;
      const float* x = (const float*)ins[0].data;
      const float* dy = (const float*)ins[2].data;
      float* dx = (float*)outs[0].data;
      int64_t n = ptop_numel(&ins[0]);
      for (int64_t i = 0; i < n; ++i)
        dx[i] = x[i] > 0.f ? 2.f * x[i] * dy[i] : 0.f;
      return 0;
    }
""")


@pytest.fixture(scope="module")
def relu2(tmp_path_factory):
    d = tmp_path_factory.mktemp("custom_op")
    src = os.path.join(d, "relu2_op.cc")
    with open(src, "w") as f:
        f.write(_SRC)
    return cpp_extension.load(name="relu2", sources=[src],
                              build_dir=None, register=True)


def test_custom_op_eager(relu2, rng):
    x = rng.normal(size=(4, 5)).astype(np.float32)
    y = np.asarray(relu2(jnp.asarray(x)))
    np.testing.assert_allclose(y, np.maximum(x, 0) ** 2, rtol=1e-6)


def test_custom_op_under_jit(relu2, rng):
    x = rng.normal(size=(8,)).astype(np.float32)
    f = jax.jit(lambda a: relu2(a) + 1.0)
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))),
                               np.maximum(x, 0) ** 2 + 1.0, rtol=1e-6)


def test_custom_op_grad_via_c_backward(relu2, rng):
    x = rng.normal(size=(6,)).astype(np.float32)
    g = jax.grad(lambda a: relu2(a).sum())(jnp.asarray(x))
    expect = np.where(x > 0, 2 * x, 0.0)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-5)


def test_custom_op_infer_shape_from_c(relu2):
    # C infer fn drives output avals: works under eval_shape (no exec)
    out = jax.eval_shape(relu2._call, jax.ShapeDtypeStruct((3, 7),
                                                           jnp.float32))
    assert out.shape == (3, 7) and out.dtype == jnp.float32


def test_custom_op_registered(relu2):
    from paddle_tpu.ops import get_op
    od = get_op("relu2")
    assert od.module == "custom" and od.differentiable


def test_custom_op_shape_fn_python(tmp_path, rng):
    # shape_fn path: no C infer symbol needed
    src = tmp_path / "twice_op.cc"
    src.write_text(textwrap.dedent("""
        #include "pt_custom_op.h"
        PT_BUILD_OP(twice) {
          const float* x = (const float*)ins[0].data;
          float* y = (float*)outs[0].data;
          for (int64_t i = 0; i < ptop_numel(&ins[0]); ++i)
            y[i] = 2.f * x[i];
          return 0;
        }
    """))
    op = cpp_extension.load(
        name="twice", sources=[str(src)],
        shape_fn=lambda x: [(x[0], x[1])], register=False)
    x = rng.normal(size=(3,)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(op(jnp.asarray(x))), 2 * x,
                               rtol=1e-6)


def test_custom_op_works_with_tensor(relu2):
    import paddle_tpu as pt
    t = pt.Tensor(np.array([1.0, -2.0], np.float32))
    out = relu2(t)
    assert isinstance(out, pt.Tensor)
    np.testing.assert_allclose(np.asarray(out.value), [1.0, 0.0])
