"""Distributed / hybrid-parallel tests on the virtual 8-device CPU mesh.

Mirrors the reference's hybrid-parallel test pattern
(unittests/hybrid_parallel_mp_layers.py: sharded-layer output equals the
single-device baseline; hybrid_parallel_communicate_group.py topology
checks) — but in-process over fake devices instead of subprocesses.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
from paddle_tpu import nn
from paddle_tpu.distributed import (DistributedStrategy, fleet,
                                    CommunicateTopology,
                                    create_hybrid_communicate_group)
from paddle_tpu.distributed.topology import get_hybrid_communicate_group


@pytest.fixture(scope="module", autouse=True)
def hybrid_env():
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                        "sharding_degree": 2}
    s.sharding = True
    fleet.init(strategy=s)
    yield


def test_topology_rank_math():
    topo = CommunicateTopology(("data", "pipe", "model"), (2, 2, 2))
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 1)
    groups = topo.get_comm_list("model")
    assert [0, 1] in groups and [6, 7] in groups
    assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]


def test_hcg_axes():
    hcg = get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_sharding_parallel_world_size() == 2
    assert hcg.mesh.shape["mp"] == 2
    assert hcg.get_parallel_mode() == "sharding_parallel"


def test_column_row_parallel_match_dense():
    """TP layers' sharded pjit result == plain dense computation."""
    from paddle_tpu.distributed.mp_layers import (ColumnParallelLinear,
                                                  RowParallelLinear)

    pt.seed(0)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)
    x = np.random.default_rng(0).standard_normal((4, 16)).astype(np.float32)

    # dense reference
    ref = (x @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy() \
        + row.bias.numpy()

    from paddle_tpu.nn import functional_call, functional_state

    class Both(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col, self.row = col, row

        def forward(self, v):
            return self.row(self.col(v))

    both = Both()
    state = functional_state(both)
    hcg = get_hybrid_communicate_group()

    @jax.jit
    def fwd(params, xv):
        return functional_call(both, {"params": params, "buffers": {}},
                               pt.Tensor(xv))

    out = fwd(state["params"], jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_sharded_train_step_gpt():
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    pt.seed(1)
    model = GPTForCausalLM(gpt_tiny())
    opt = optim.AdamW(learning_rate=3e-4)
    step = fleet.distributed_jit(model, opt,
                                 lambda m, b: m(b[0], labels=b[1]))
    ids = (np.arange(8 * 32).reshape(8, 32) % 1000).astype(np.int32)
    losses = [float(step((ids, ids))) for _ in range(4)]
    assert losses[-1] < losses[0]
    # qkv weight is mp-sharded on its output dim
    spec = step.param_shardings["gpt.h.0.attn.qkv_proj.weight"].spec
    assert spec == P(None, "mp")
    # adam slots of a replicated param are ZeRO-sharded over "sharding"
    slot_shard = step.opt_shardings["slots"]["gpt.wpe.weight"]["moment1"]
    assert slot_shard.spec == P("sharding", None)


@pytest.mark.slow
def test_sharded_matches_single_device():
    """Hybrid-parallel loss == single-device TrainStep loss (the
    reference's core hybrid test invariant)."""
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    ids = (np.arange(4 * 32).reshape(4, 32) % 1000).astype(np.int32)

    pt.seed(42)
    m1 = GPTForCausalLM(gpt_tiny())
    o1 = optim.SGD(learning_rate=0.1)
    s1 = TrainStep(m1, o1, lambda m, b: m(b[0], labels=b[1]))
    l1 = [float(s1((ids, ids))) for _ in range(3)]

    pt.seed(42)
    m2 = GPTForCausalLM(gpt_tiny())
    o2 = optim.SGD(learning_rate=0.1)
    s2 = fleet.distributed_jit(m2, o2, lambda m, b: m(b[0], labels=b[1]))
    l2 = [float(s2((ids, ids))) for _ in range(3)]

    np.testing.assert_allclose(l1, l2, rtol=2e-3, atol=2e-4)


def test_collectives_in_shard_map():
    from paddle_tpu.compat import shard_map
    from paddle_tpu.distributed import collective as C

    hcg = get_hybrid_communicate_group()
    mesh = hcg.mesh
    x = jnp.arange(8.0)

    def body(v):
        s = C.all_reduce(v, group="dp")
        g = C.all_gather(v, group="dp", axis=0)
        return s, g

    out_s, out_g = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("dp"), out_specs=(P("dp"), P()),
        check_vma=False))(x)
    # dp axis has size 2: halves summed elementwise
    first, second = np.asarray(x[:4]), np.asarray(x[4:])
    np.testing.assert_allclose(np.asarray(out_s),
                               np.concatenate([first + second] * 2))
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(x))


def test_ring_attention_matches_full():
    from paddle_tpu.compat import shard_map
    from paddle_tpu.distributed.sp import ring_attention
    from paddle_tpu.ops.nn_functional import scaled_dot_product_attention

    hcg = get_hybrid_communicate_group()
    mesh = hcg.mesh
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 8, 2, 4
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)

    full = scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                        jnp.asarray(v), is_causal=True)

    ring = jax.jit(shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, axis_name="mp",
                                        causal=True),
        mesh=mesh, in_specs=P(None, "mp"), out_specs=P(None, "mp"),
        check_vma=False))
    out = ring(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=2e-3, atol=2e-3)


def test_ulysses_attention_matches_full():
    from paddle_tpu.compat import shard_map
    from paddle_tpu.distributed.sp import ulysses_attention
    from paddle_tpu.ops.nn_functional import scaled_dot_product_attention

    hcg = get_hybrid_communicate_group()
    mesh = hcg.mesh
    rng = np.random.default_rng(1)
    b, s, h, d = 2, 8, 4, 4  # h divisible by axis size 2
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)

    full = scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                        jnp.asarray(v), is_causal=True)
    uly = jax.jit(shard_map(
        lambda a, b_, c: ulysses_attention(a, b_, c, axis_name="mp",
                                           causal=True),
        mesh=mesh, in_specs=P(None, "mp"), out_specs=P(None, "mp"),
        check_vma=False))
    out = uly(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=2e-3, atol=2e-3)


def test_spmd_pipeline_matches_sequential():
    from paddle_tpu.compat import shard_map
    from paddle_tpu.distributed.pp import (pipeline_last_stage_value,
                                           spmd_pipeline)

    # 2-stage pipeline over the "dp" axis (size 2): y = relu(x@W_s + b_s)
    mesh = get_hybrid_communicate_group().mesh
    rng = np.random.default_rng(0)
    n_stages, n_micro, mb, dim = 2, 4, 2, 8
    Ws = rng.standard_normal((n_stages, dim, dim)).astype(np.float32) * 0.5
    xs = rng.standard_normal((n_micro, mb, dim)).astype(np.float32)

    def stage_fn(w, x):
        return jax.nn.relu(x @ w)

    # sequential reference
    ref = xs
    for i in range(n_stages):
        ref = jax.nn.relu(ref @ Ws[i])

    def run(w_all, x_micro):
        w_local = w_all[0]  # shard_map gives [1, ...] per device on dp
        outs = spmd_pipeline(stage_fn, w_local, x_micro, axis_name="dp")
        return pipeline_last_stage_value(outs, "dp")

    out = jax.jit(shard_map(
        run, mesh=mesh, in_specs=(P("dp"), P()), out_specs=P(),
        check_vma=False))(jnp.asarray(Ws), jnp.asarray(xs))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_recompute_matches_plain():
    from paddle_tpu.distributed import recompute
    from paddle_tpu.nn import functional_call, functional_state

    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 8))
    state = functional_state(net)
    x = np.random.default_rng(0).standard_normal((4, 8)).astype(np.float32)

    def loss_plain(params):
        out = functional_call(net, {"params": params, "buffers": {}},
                              pt.Tensor(x))
        return jnp.sum(out ** 2)

    def loss_remat(params):
        from paddle_tpu.nn.layer import bind_state
        from paddle_tpu.autograd.engine import no_grad
        with bind_state(net, {"params": params, "buffers": {}}), no_grad():
            out = recompute(net, pt.Tensor(x))
        return jnp.sum(out.value ** 2)

    g1 = jax.grad(loss_plain)(state["params"])
    g2 = jax.grad(loss_remat)(state["params"])
    for k_ in g1:
        np.testing.assert_allclose(np.asarray(g1[k_]), np.asarray(g2[k_]),
                                   rtol=1e-5)


@pytest.mark.slow
def test_gradient_merge_step():
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                        "sharding_degree": 2}
    s.gradient_merge = True
    s.gradient_merge_configs = {"k_steps": 2}
    pt.seed(5)
    model = GPTForCausalLM(gpt_tiny())
    opt = optim.SGD(learning_rate=0.05)
    step = fleet.distributed_jit(model, opt,
                                 lambda m, b: m(b[0], labels=b[1]),
                                 strategy=s)
    ids = (np.arange(8 * 32).reshape(8, 32) % 1000).astype(np.int32)
    for _ in range(2):
        step((ids, ids))
    assert int(step.opt_state["step"]) == 2


def test_zigzag_permutation_roundtrip():
    from paddle_tpu.distributed.sp import (zigzag_permutation,
                                           zigzag_positions)

    perm, inv = zigzag_permutation(32, 4)
    x = np.arange(32)
    np.testing.assert_array_equal(x[perm][inv], x)
    # device i's local shard holds original half-chunks i and 2n-1-i
    s_loc = 32 // 4
    for i in range(4):
        local = perm[i * s_loc:(i + 1) * s_loc]
        expect = np.asarray(zigzag_positions(i, 4, s_loc))
        np.testing.assert_array_equal(local, expect)
    # n=1 is identity
    p1, i1 = zigzag_permutation(8, 1)
    np.testing.assert_array_equal(p1, np.arange(8))


def test_zigzag_ring_matches_full():
    from paddle_tpu.compat import shard_map
    from paddle_tpu.distributed.sp import ring_attention, zigzag_permutation
    from paddle_tpu.ops.nn_functional import scaled_dot_product_attention

    hcg = get_hybrid_communicate_group()
    mesh = hcg.mesh
    n = 2  # the fixture mesh's mp axis size
    rng = np.random.default_rng(3)
    b, s, h, d = 2, 16, 2, 4
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)

    full = scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                        jnp.asarray(v), is_causal=True)
    perm, inv = zigzag_permutation(s, n)
    ring = jax.jit(shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, axis_name="mp",
                                        causal=True, layout="zigzag"),
        mesh=mesh, in_specs=P(None, "mp"), out_specs=P(None, "mp"),
        check_vma=False))
    out_z = ring(jnp.asarray(q[:, perm]), jnp.asarray(k[:, perm]),
                 jnp.asarray(v[:, perm]))
    np.testing.assert_allclose(np.asarray(out_z)[:, inv],
                               np.asarray(full), rtol=2e-3, atol=2e-3)


def test_zigzag_schedule_is_balanced():
    """The measured claim behind the layout (r3 verdict weak #3): the
    lockstep critical path (sum over hops of the per-hop max work)
    improves ~2x, and per-device totals are exactly equal."""
    from paddle_tpu.distributed.sp import ring_schedule_work

    n = 8
    cont = ring_schedule_work(n, "contiguous")
    zig = ring_schedule_work(n, "zigzag")
    crit_c = sum(max(row) for row in cont)
    crit_z = sum(max(row) for row in zig)
    assert crit_c == 2 + 4 * (n - 1)  # one diag hop + full hops
    assert crit_z == 2 * n
    assert crit_c / crit_z >= 1.8
    # total FLOPs identical (same causal attention, re-laid-out)
    assert sum(map(sum, cont)) == sum(map(sum, zig))
    # zigzag: every device does identical work at every hop
    assert all(len(set(row)) == 1 for row in zig)


def test_zigzag_eager_fallback_matches_dense_model():
    """Eager (untraced) forward of a zigzag-mode GPT must match the
    dense model: the fallback un-permutes before causal masking
    (regression: permuted tokens under a row>=col mask)."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    def cfg(mode):
        return GPTConfig(vocab_size=97, hidden_size=16, num_layers=1,
                         num_heads=2, max_seq_len=32, dropout=0.0,
                         attn_dropout=0.0, seq_parallel_mode=mode)

    ids = (np.arange(2 * 32).reshape(2, 32) % 97).astype(np.int32)
    pt.seed(3)
    dense = GPTForCausalLM(cfg(None))
    pt.seed(3)
    zig = GPTForCausalLM(cfg("zigzag"))
    l_dense = float(dense(pt.to_tensor(ids), labels=pt.to_tensor(ids)))
    l_zig = float(zig(pt.to_tensor(ids), labels=pt.to_tensor(ids)))
    np.testing.assert_allclose(l_zig, l_dense, rtol=1e-4)


def test_zigzag_reorder_matches_permutation():
    from paddle_tpu.distributed.sp import (zigzag_permutation,
                                           zigzag_reorder)

    x = np.arange(2 * 32 * 3).reshape(2, 32, 3).astype(np.float32)
    perm, inv = zigzag_permutation(32, 4)
    np.testing.assert_array_equal(
        np.asarray(zigzag_reorder(jnp.asarray(x), 4, axis=1)), x[:, perm])
    np.testing.assert_array_equal(
        np.asarray(zigzag_reorder(jnp.asarray(x[:, perm]), 4, axis=1,
                                  inverse=True)), x)
