"""Serving layer (paddle_tpu/serving): prefix cache, SLO scheduler,
socket server, per-request observability, fault robustness.

The two contracts the suite pins (ISSUE r7 acceptance):

- greedy outputs with prefix caching are BIT-IDENTICAL to the uncached
  engine for the same request stream, and `PageAllocator.check_no_leak`
  passes after drain in every serving test;
- with ``serving.prefill`` faults armed the server retries transients,
  sheds on overload with a typed reply, and drains cleanly — no leaked
  pages, no hung clients.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed import fault_inject as fi
from paddle_tpu.inference import PageAllocator, create_decode_engine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (Priority, PrefixCache, ServerOverloaded,
                                ServingMetrics, ServingServer, SLOConfig,
                                SLOScheduler, client_request)


@pytest.fixture(autouse=True)
def _clean_injector():
    fi.reset()
    yield
    fi.reset()


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests (see
    conftest.module_compile_cache) — most of this file's tier-1 wall
    cost is repeated compiles of the same gpt_tiny shapes."""
    yield


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _engine(m, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("num_pages", 12)
    return create_decode_engine(m, **kw)


def _shared_prefix_prompts(shared_len=19, tails=(3, 5, 7, 9)):
    shared = (np.arange(shared_len, dtype=np.int32) * 5) % 100
    return [np.concatenate([shared,
                            (np.arange(t, dtype=np.int32) + 3 * t) % 100])
            for t in tails]


# ---------------------------------------------------------------------------
# Prefix cache: unit semantics (no model)
# ---------------------------------------------------------------------------

class TestPrefixCacheUnit:
    def test_shareable_blocks_never_cover_whole_prompt(self):
        pc = PrefixCache(8)
        # 16 tokens = 2 full pages, but the last token must stay in
        # the suffix -> only 1 shareable block
        assert pc._shareable_blocks(np.arange(16)) == 1
        assert pc._shareable_blocks(np.arange(17)) == 2
        assert pc._shareable_blocks(np.arange(8)) == 0
        assert pc._shareable_blocks(np.arange(9)) == 1

    def test_match_insert_refcount_evict_cycle(self):
        pc = PrefixCache(4)
        alloc = PageAllocator(8)
        prompt = np.arange(11, dtype=np.int32)  # 2 shareable blocks
        assert pc.match(prompt) == ((), [])
        pages = alloc.alloc("req0", 3)
        row = np.array(pages + [99], dtype=np.int32)
        keys = pc.insert(prompt, row, alloc, "req0", 4, ())
        assert len(keys) == 2 and pc.total_pages() == 2
        # the two full pages now belong to the cache, not the request
        assert sum(len(v) for k, v in alloc.owners().items()
                   if k == "req0") == 1
        mk, mp = pc.match(prompt)
        assert mk == keys and mp == [int(row[0]), int(row[1])]
        # referenced entries are not evictable
        assert pc.evictable_pages() == 0
        assert not pc.evict_until(alloc, alloc.num_pages)
        pc.release(keys)
        assert pc.evictable_pages() == 2
        # leaf-first LRU teardown
        assert pc.evict_until(alloc, alloc.free_count + 2)
        assert pc.total_pages() == 0
        alloc.free("req0")
        alloc.check_no_leak()

    def test_divergent_prompt_shares_only_common_blocks(self):
        pc = PrefixCache(4)
        alloc = PageAllocator(8)
        a = np.arange(11, dtype=np.int32)
        b = np.concatenate([a[:4], a[4:] + 50])  # diverges in block 1
        row = np.array(alloc.alloc("a", 3) + [99], dtype=np.int32)
        keys = pc.insert(a, row, alloc, "a", 4, ())
        mk, mp = pc.match(b)
        assert len(mk) == 1 and mp == [int(row[0])]
        pc.release(keys)
        pc.clear(alloc)
        alloc.free("a")
        alloc.check_no_leak()

    def test_clear_refuses_referenced_entries(self):
        pc = PrefixCache(4)
        alloc = PageAllocator(4)
        row = np.array(alloc.alloc("a", 2) + [0, 0], dtype=np.int32)
        pc.insert(np.arange(9, dtype=np.int32), row, alloc, "a", 4, ())
        with pytest.raises(RuntimeError, match="still referenced"):
            pc.clear(alloc)

    def test_allocator_transfer_bookkeeping(self):
        alloc = PageAllocator(4)
        pages = alloc.alloc(1, 3)
        alloc.transfer(1, ("prefix", b"k"), pages[:2])
        assert alloc.owners()[("prefix", b"k")] == tuple(pages[:2])
        with pytest.raises(RuntimeError, match="not owned"):
            alloc.transfer(1, 2, [pages[0]])
        alloc.free(1)
        alloc.free(("prefix", b"k"))
        alloc.check_no_leak()


# ---------------------------------------------------------------------------
# Prefix cache through the engine: the bit-identical contract
# ---------------------------------------------------------------------------

class TestPrefixCacheEngine:
    def test_cached_outputs_bit_identical_to_uncached(self, model):
        """Same request stream, prefix cache on vs off: greedy tokens
        must match bit for bit (the acceptance pin). More requests
        than slots so recycling and mid-flight admission are live."""
        prompts = _shared_prefix_prompts()
        eng0 = _engine(model)
        out0 = None
        rids0 = [eng0.submit(p, max_new_tokens=12) for p in prompts]
        out0 = eng0.run()
        eng0.close()
        pc = PrefixCache(8)
        eng1 = _engine(model, prefix_cache=pc)
        rids1 = [eng1.submit(p, max_new_tokens=12) for p in prompts]
        out1 = eng1.run()
        assert pc.hit_pages > 0  # the shared prefix was actually reused
        for r0, r1 in zip(rids0, rids1):
            np.testing.assert_array_equal(out0[r0], out1[r1])
        eng1.close()
        eng1.allocator.check_no_leak()

    def test_cache_survives_batches_and_skips_prefill_pages(self, model):
        """Second wave with the same system prompt hits the cache
        (pages survive request completion at refcount 0) and still
        matches the per-sequence dense reference."""
        pc = PrefixCache(8)
        eng = _engine(model, prefix_cache=pc)
        prompts = _shared_prefix_prompts(tails=(3, 6))
        r0 = [eng.submit(p, max_new_tokens=10) for p in prompts]
        eng.run()
        hits_before = pc.hit_pages
        prompts2 = _shared_prefix_prompts(tails=(4, 8))
        r2 = [eng.submit(p, max_new_tokens=10) for p in prompts2]
        out = eng.run()
        assert pc.hit_pages > hits_before
        for p, rid in zip(prompts2, r2):
            ref = model.generate(pt.Tensor(p[None]), max_new_tokens=10,
                                 temperature=0.0, use_jit=True,
                                 kv_cache="paged", page_size=8)
            np.testing.assert_array_equal(out[rid],
                                          np.asarray(ref.value)[0])
        stats = eng.result(r0[0])  # drained store popped by run()
        assert stats is None
        eng.close()
        eng.allocator.check_no_leak()

    def test_page_size_mismatch_rejected_at_construction(self, model):
        with pytest.raises(ValueError, match="page_size"):
            _engine(model, prefix_cache=PrefixCache(16))  # engine is 8

    def test_cache_eviction_under_page_pressure(self, model):
        """A pool too small to keep the cache AND serve a new request:
        refcount-0 entries are LRU-evicted so admission proceeds;
        outputs stay correct."""
        pc = PrefixCache(8)
        eng = _engine(model, num_pages=6, prefix_cache=pc)
        a = (np.arange(17, dtype=np.int32) * 3) % 100
        ra = eng.submit(a, max_new_tokens=8)   # needs 4 pages, caches 2
        eng.run()
        assert pc.total_pages() == 2
        b = (np.arange(20, dtype=np.int32) * 7 + 1) % 100
        # 20 + 15 = 35 tokens -> 5 pages, but only 4 are free: the
        # cache must LRU-evict to admit
        rb = eng.submit(b, max_new_tokens=15)
        out = eng.run()
        assert pc.evicted_pages >= 1
        ref = model.generate(pt.Tensor(b[None]), max_new_tokens=15,
                             temperature=0.0, use_jit=True)
        np.testing.assert_array_equal(out[rb], np.asarray(ref.value)[0])
        assert ra != rb
        eng.close()
        eng.allocator.check_no_leak()


# ---------------------------------------------------------------------------
# Engine lifecycle: streaming, stats, close()
# ---------------------------------------------------------------------------

class TestEngineLifecycle:
    def test_streaming_matches_final_sequence(self, model):
        """Satellite: streamed token sequence == final returned
        sequence for greedy decode, ragged batch with a MID-FLIGHT
        admit; the last streamed token carries done=True."""
        eng = _engine(model)
        streamed = {}
        flags = {}

        def cb(rid, tok, done):
            streamed.setdefault(rid, []).append(tok)
            flags.setdefault(rid, []).append(done)

        r0 = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=10,
                        on_token=cb)
        eng.step()
        eng.step()
        # mid-flight admission while r0 is decoding
        r1 = eng.submit((np.arange(9, dtype=np.int32) * 3) % 100,
                        max_new_tokens=6, on_token=cb)
        out = eng.run()
        for rid in (r0, r1):
            gen = out[rid][len(out[rid]) -
                           len(streamed[rid]):]
            np.testing.assert_array_equal(np.asarray(streamed[rid]), gen)
            assert flags[rid][-1] is True
            assert not any(flags[rid][:-1])
        eng.close()

    def test_per_request_stats_record(self, model):
        """Satellite: admit time, prefill ms, first-token time and
        tokens emitted are exposed on completion."""
        done = []
        eng = _engine(model, on_complete=done.append)
        eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=4)
        eng.run()
        (req,) = done
        st = req.stats
        assert req.state == "done" and st.tokens_out == 4
        assert st.submit_t <= st.admit_t <= st.first_token_t \
            <= st.finish_t
        assert st.prefill_ms > 0 and st.prefill_attempts == 1
        d = st.to_dict()
        assert d["ttft_s"] >= 0 and d["queue_delay_s"] >= 0
        assert d["tpot_s"] >= 0 and d["prompt_len"] == 5
        eng.close()

    def test_close_mid_flight_evicts_and_frees(self, model):
        """Satellite: close() evicts active slots, returns their
        pages, and passes check_no_leak — the early-exit path that
        used to leak engine state."""
        done = []
        eng = _engine(model, on_complete=done.append)
        eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=30)
        eng.submit(np.arange(9, dtype=np.int32), max_new_tokens=30)
        eng.submit(np.arange(60, dtype=np.int32), max_new_tokens=30)
        eng.step()
        assert eng.num_active > 0
        eng.close()  # asserts check_no_leak internally
        assert eng.num_active == 0 and eng.num_queued == 0
        states = {r.state for r in done}
        assert states == {"evicted"}
        assert len(done) == 3


# ---------------------------------------------------------------------------
# SLO scheduler
# ---------------------------------------------------------------------------

class _FakeReq:
    def __init__(self, rid, submit_t, priority=Priority.NORMAL):
        from paddle_tpu.inference.continuous_batching import RequestStats
        self.req_id = rid
        self.priority = int(priority)
        self.stats = RequestStats(submit_t=submit_t)
        self.bypass_count = 0
        self.state = "queued"
        self.done = False


class TestSLOScheduler:
    def test_priority_order_and_promotion(self):
        s = SLOScheduler(SLOConfig(promote_after_s=1.0))
        now = 100.0
        batch_old = _FakeReq(0, now - 2.5, Priority.BATCH)
        inter_new = _FakeReq(1, now - 0.1, Priority.INTERACTIVE)
        norm_new = _FakeReq(2, now - 0.1, Priority.NORMAL)
        q = [batch_old, norm_new, inter_new]
        # aged BATCH promoted to INTERACTIVE ties with the interactive
        # request; earlier arrival wins
        assert s.effective_priority(batch_old, now) == Priority.INTERACTIVE
        assert s.select(q, lambda r: True, now) == 0
        # without aging, interactive wins over normal
        q2 = [norm_new, inter_new]
        assert s.select(q2, lambda r: True, now) == 1

    def test_bounded_fairness_blocks_bypass(self):
        s = SLOScheduler(SLOConfig(max_bypass=2, promote_after_s=1e9))
        now = 10.0
        big = _FakeReq(0, now - 1.0)          # never fits (yet)
        fits = lambda r: r is not big          # noqa: E731
        q = [big, _FakeReq(1, now), _FakeReq(2, now), _FakeReq(3, now)]
        # admission COMMITS charge the bypass (note_admitted), exactly
        # as the engine drives it
        idx = s.select(q, fits, now)
        assert idx == 1
        s.note_admitted(q.pop(idx), q, now)    # bypass 1
        idx = s.select(q, fits, now)
        assert idx == 1
        s.note_admitted(q.pop(idx), q, now)    # bypass 2
        # big now at max_bypass: nothing else may jump it
        assert s.select(q, fits, now) is None
        assert s.select(q, lambda r: True, now) == 0

    def test_failed_admission_charges_no_bypass(self):
        """select() alone must NOT move bypass_count — an admission
        that later unwinds would otherwise flip the queue into
        starved-only mode with no real jump having happened."""
        s = SLOScheduler(SLOConfig(max_bypass=2, promote_after_s=1e9))
        now = 10.0
        big = _FakeReq(0, now - 1.0)
        q = [big, _FakeReq(1, now)]
        for _ in range(10):
            assert s.select(q, lambda r: r is not big, now) == 1
        assert big.bypass_count == 0

    def test_shed_and_admission_check(self):
        s = SLOScheduler(SLOConfig(shed_after_s=5.0, max_queue=2))
        now = 50.0
        fresh, stale = _FakeReq(0, now - 1), _FakeReq(1, now - 9)
        assert s.shed([fresh, stale], now) == [stale]
        s.check_admission(1)
        with pytest.raises(ServerOverloaded) as ei:
            s.check_admission(2)
        assert ei.value.retry_after_ms > 0

    def test_engine_shed_marks_state(self, model):
        done = []
        sched = SLOScheduler(SLOConfig(shed_after_s=0.0))
        eng = _engine(model, scheduler=sched, on_complete=done.append)
        eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=4)
        time.sleep(0.01)
        eng.run()
        assert [r.state for r in done] == ["shed"]
        eng.close()


# ---------------------------------------------------------------------------
# Socket server (CI fast-lane smoke: in-process loopback, 3 clients)
# ---------------------------------------------------------------------------

class TestServer:
    def _serve(self, model, **kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("page_size", 8)
        kw.setdefault("max_seq_len", 96)
        kw.setdefault("num_pages", 12)
        # fresh registry: counters must not bleed across tests through
        # the process-global StatRegistry
        kw.setdefault("metrics", ServingMetrics(registry=StatRegistry()))
        return ServingServer(model, **kw)

    def test_three_concurrent_clients_end_to_end(self, model):
        srv = self._serve(model)
        port = srv.start()
        results = {}

        def client(i):
            toks = []
            rep = client_request("127.0.0.1", port, {
                "op": "generate", "prompt": list(range(1, 6 + i)),
                "max_new_tokens": 6, "stream": True,
                "priority": "interactive"}, on_token=toks.append)
            results[i] = (rep, toks)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        assert len(results) == 3
        for i, (rep, toks) in results.items():
            assert "error" not in rep, rep
            assert rep["generated"] == toks
            assert rep["stats"]["tokens_out"] == 6
        h = client_request("127.0.0.1", port, {"op": "health"})
        assert h["status"] == "ok" and h["free_pages"] == 12
        st = client_request("127.0.0.1", port, {"op": "stats"})
        assert st["stats"]["counters"]["requests_total"] == 3
        assert st["stats"]["counters"]["tokens_generated_total"] == 18
        mx = client_request("127.0.0.1", port, {"op": "metrics"})
        assert "serving_ttft_ms_bucket" in mx["text"]
        assert "serving_requests_total 3" in mx["text"]
        # the reply IS the delivery: the engine must not retain
        # finished requests for the server's lifetime
        assert not srv.engine._finished
        srv.stop()  # graceful drain; close() asserts check_no_leak
        srv.engine.allocator.check_no_leak()

    def test_bad_requests_get_typed_replies(self, model):
        srv = self._serve(model)
        port = srv.start()
        cases = [
            ({"op": "generate", "prompt": []}, "BadRequest"),
            ({"op": "generate", "prompt": [1], "max_new_tokens": 0},
             "BadRequest"),
            ({"op": "generate", "prompt": [1], "priority": "vip"},
             "BadRequest"),
            ({"op": "nope"}, "BadRequest"),
            ({"op": "generate", "prompt": [1] * 95,
              "max_new_tokens": 50}, "BadRequest"),  # > max_seq_len
            # non-integer prompt elements die in np.asarray on the
            # ENGINE thread — must cost this client a BadRequest, not
            # the thread every other client depends on
            ({"op": "generate", "prompt": [None],
              "max_new_tokens": 2}, "BadRequest"),
        ]
        for payload, err in cases:
            rep = client_request("127.0.0.1", port, payload)
            assert rep.get("error") == err, (payload, rep)
        # the engine thread survived all of the above
        rep = client_request("127.0.0.1", port, {
            "op": "generate", "prompt": [1, 2, 3], "max_new_tokens": 2})
        assert "error" not in rep and len(rep["generated"]) == 2
        srv.stop()

    def test_drain_rejects_new_finishes_inflight(self, model):
        srv = self._serve(model)
        port = srv.start()
        got = {}

        def slow_client():
            got["rep"] = client_request("127.0.0.1", port, {
                "op": "generate", "prompt": [1, 2, 3],
                "max_new_tokens": 12})

        t = threading.Thread(target=slow_client)
        t.start()
        time.sleep(0.05)
        rep = client_request("127.0.0.1", port, {"op": "drain"})
        assert rep.get("status") == "draining"
        rep2 = client_request("127.0.0.1", port, {
            "op": "generate", "prompt": [4], "max_new_tokens": 2})
        assert rep2.get("error") == "ServerDraining"
        t.join(timeout=180)
        assert "error" not in got["rep"], got["rep"]
        assert len(got["rep"]["generated"]) == 12
        srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_persistent_engine_failure_escalates_typed(self, model):
        """A decode step that fails every time must not wedge clients:
        past max_engine_errors the server fails everything with a
        typed reply and stops admitting. max_engine_restarts=0 turns
        resurrection OFF so this pins the terminal escalation path
        (the resurrection path is pinned in
        tests/test_crash_safe_serving.py)."""
        srv = self._serve(model, max_engine_errors=2,
                          max_engine_restarts=0)
        port = srv.start()

        def boom():
            raise RuntimeError("decode jit broken")

        srv.engine.step = boom
        rep = client_request("127.0.0.1", port, {
            "op": "generate", "prompt": [1, 2, 3],
            "max_new_tokens": 4}, timeout_s=60)
        assert rep.get("error") in ("EngineFailed", "ServerEvicted"), rep
        h = client_request("127.0.0.1", port, {"op": "health"})
        assert h["status"] == "draining"
        rep2 = client_request("127.0.0.1", port, {
            "op": "generate", "prompt": [4], "max_new_tokens": 2})
        assert rep2.get("error") == "ServerDraining"
        srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_overload_sheds_with_typed_reply(self, model):
        srv = self._serve(
            model, scheduler=SLOScheduler(SLOConfig(max_queue=1)))
        port = srv.start()
        outcomes = []
        lock = threading.Lock()

        def client(i):
            rep = client_request("127.0.0.1", port, {
                "op": "generate", "prompt": list(range(1, 30)),
                "max_new_tokens": 12})
            with lock:
                outcomes.append(rep)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        shed = [r for r in outcomes
                if r.get("error") == "ServerOverloaded"]
        ok = [r for r in outcomes if "error" not in r]
        assert len(outcomes) == 6
        assert shed, outcomes  # at least one typed overload reply
        assert ok              # and the system still served work
        assert all("retry_after_ms" in r for r in shed)
        srv.stop()
        srv.engine.allocator.check_no_leak()


# ---------------------------------------------------------------------------
# Fault injection: serving.request / serving.prefill (acceptance)
# ---------------------------------------------------------------------------

class TestServingFaults:
    def test_prefill_transient_retried_bit_identical(self, model):
        """One injected transient at serving.prefill: the site policy
        retries it invisibly; output matches the fault-free run."""
        from paddle_tpu.distributed.resilience import get_retry_policy
        prompt = np.arange(5, dtype=np.int32)
        eng0 = _engine(model)
        r0 = eng0.submit(prompt, max_new_tokens=6)
        ref = eng0.run()[r0]
        eng0.close()

        fi.get_injector().arm("serving.prefill", at_calls=[1])
        eng = _engine(
            model, prefill_retry=get_retry_policy("serving.prefill"))
        r = eng.submit(prompt, max_new_tokens=6)
        out = eng.run()
        assert fi.get_injector().counts("serving.prefill")["fired"] == 1
        np.testing.assert_array_equal(out[r], ref)
        eng.close()

    def test_prefill_persistent_fault_fails_request_typed(self, model):
        """Every prefill attempt faults: after max_prefill_attempts
        admission rounds the request FAILS (typed, observable) instead
        of wedging the queue; pages all return."""
        from paddle_tpu.distributed.resilience import RetryPolicy
        fi.get_injector().arm("serving.prefill", probability=1.0)
        done = []
        eng = _engine(model, on_complete=done.append,
                      prefill_retry=RetryPolicy(max_attempts=2,
                                                base_delay_s=0.0),
                      max_prefill_attempts=2)
        eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=4)
        for _ in range(4):
            try:
                eng.step()
            except Exception:
                pass
            if done:
                break
        assert [r.state for r in done] == ["failed"]
        assert done[0].stats.prefill_attempts == 2
        eng.close()
        eng.allocator.check_no_leak()

    def test_server_under_prefill_faults_no_hung_clients(self, model):
        """Acceptance: faults armed on serving.prefill AND
        serving.request, six concurrent clients — every client gets a
        terminal reply (success or typed error), the server drains
        clean, zero pages leak."""
        fi.get_injector().arm("serving.prefill", probability=0.5,
                              max_faults=3, seed=7)
        fi.get_injector().arm("serving.request", at_calls=[2])
        srv = ServingServer(model, num_slots=2, page_size=8,
                            max_seq_len=96, num_pages=12,
                            metrics=ServingMetrics(
                                registry=StatRegistry()))
        port = srv.start()
        outcomes = []
        lock = threading.Lock()

        def client(i):
            rep = client_request("127.0.0.1", port, {
                "op": "generate", "prompt": list(range(1, 7 + i)),
                "max_new_tokens": 5}, timeout_s=180)
            with lock:
                outcomes.append(rep)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=240)
        assert len(outcomes) == 6  # nobody hung
        ok = [r for r in outcomes if "error" not in r]
        typed = [r for r in outcomes if "error" in r]
        assert len(ok) >= 4  # transients retried; most work finishes
        for r in typed:
            assert r["error"] in ("TransientServerError",
                                  "PrefillFailed")
        srv.stop()
        srv.engine.allocator.check_no_leak()


# ---------------------------------------------------------------------------
# Load test (slow lane): 64 mixed requests, 50% shared system prompt
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_shared_prefix_load_64_requests(model):
    """64 mixed-length requests, half sharing a 24-token system
    prompt: prefix-cache hit rate > 0, every request completes, zero
    page leaks after drain + close."""
    pc = PrefixCache(8)
    metrics = ServingMetrics(registry=StatRegistry())
    done = []
    eng = create_decode_engine(
        model, num_slots=4, page_size=8, max_seq_len=96, num_pages=36,
        prefix_cache=pc, scheduler=SLOScheduler(),
        on_complete=lambda r: (metrics.observe_request(r),
                               done.append(r)))
    rng = np.random.default_rng(0)
    system = (np.arange(24, dtype=np.int32) * 11) % 100
    reqs = []
    for i in range(64):
        tail = rng.integers(0, 100, rng.integers(2, 30)).astype(np.int32)
        prompt = np.concatenate([system, tail]) if i % 2 == 0 else tail
        rid = eng.submit(prompt, max_new_tokens=int(rng.integers(2, 10)),
                         priority=int(rng.integers(0, 3)))
        reqs.append((rid, prompt))
    out = eng.run(max_steps=500000)
    assert len(out) == 64 and len(done) == 64
    assert all(r.state == "done" for r in done)
    assert pc.hit_rate() is not None and pc.hit_rate() > 0
    assert metrics.counter("cache_hit_pages_total").get() > 0
    snap = metrics.ttft_ms.snapshot()
    assert snap["count"] == 64 and snap["p50"] is not None
    eng.close()
    eng.allocator.check_no_leak()
