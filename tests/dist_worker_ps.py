"""Parameter-server-mode multi-process worker script.

Reference pattern: test_dist_base.py:959 _run_cluster starts pserver
subprocesses plus trainer subprocesses and checks training progress.
Here the server process hosts a PSServer (dense SGD table over real
sockets) and trainer processes run lockstep synchronous SGD on a linear
regression: pull weights, compute the local-shard gradient, push, and
rendezvous on the server-side blocking barrier — so the 2-trainer run
applies exactly the same global-batch updates as a 1-trainer run
(sync-PS semantics; async/geo modes are covered in-process by
tests/test_native_ps.py and test_heavy_dataset_geo_ps.py).

Env contract:
  PT_ROLE              "server" | "trainer"
  PT_PS_ENDPOINT_FILE  server writes host:port here; trainers poll it
  PT_PS_DONE_DIR       trainers drop rank files here; server exits when
                       all PT_PS_TRAINERS have finished
  PT_PS_TRAINERS       number of trainer processes
  PT_PS_TRAINER_ID     this trainer's id
  PT_PS_STEPS          sgd steps (default 30)
  PT_DIST_OUT          per-trainer JSON output path prefix
"""

import json
import os
import time

import numpy as np


def make_data():
    """Deterministic synthetic regression task shared by every process."""
    rng = np.random.default_rng(3)
    w_true = rng.normal(size=(8, 1)).astype(np.float32)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = x @ w_true
    return x, y


def run_server():
    from paddle_tpu.distributed.ps import PSServer
    server = PSServer()
    server.add_dense_table("w", (8, 1), optimizer="sgd", lr=0.1)
    server.start()
    with open(os.environ["PT_PS_ENDPOINT_FILE"] + ".tmp", "w") as f:
        f.write(f"{server.host}:{server.port}")
    os.replace(os.environ["PT_PS_ENDPOINT_FILE"] + ".tmp",
               os.environ["PT_PS_ENDPOINT_FILE"])
    done_dir = os.environ["PT_PS_DONE_DIR"]
    n_trainers = int(os.environ["PT_PS_TRAINERS"])
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            if len(os.listdir(done_dir)) >= n_trainers:
                break
        except FileNotFoundError:
            pass
        time.sleep(0.05)
    server.stop()


def run_trainer():
    from paddle_tpu.distributed.ps import PSClient
    ep_file = os.environ["PT_PS_ENDPOINT_FILE"]
    deadline = time.time() + 60
    while not os.path.exists(ep_file):
        if time.time() > deadline:
            raise TimeoutError("server endpoint never appeared")
        time.sleep(0.05)
    with open(ep_file) as f:
        endpoint = f.read().strip()

    tid = int(os.environ["PT_PS_TRAINER_ID"])
    world = int(os.environ["PT_PS_TRAINERS"])
    steps = int(os.environ.get("PT_PS_STEPS", "30"))

    client = PSClient([endpoint])
    x, y = make_data()
    # disjoint row shards, reference DistributedBatchSampler-style
    shard = slice(tid * (len(x) // world), (tid + 1) * (len(x) // world))
    xs, ys = x[shard], y[shard]

    if tid == 0:
        client.push_dense_init("w", np.zeros((8, 1), np.float32))
    client.barrier(world=world)  # everyone sees the initialized table

    losses = []
    for _ in range(steps):
        w = client.pull_dense("w")
        client.barrier(world=world)  # all pulls see the same w ...
        pred = xs @ w
        err = pred - ys
        losses.append(float((err ** 2).mean()))
        # grad of mean-over-global-batch MSE: each trainer contributes
        # its shard's sum / global_n, so the pushed grads add up to the
        # exact full-batch gradient
        grad = (2.0 / len(x)) * (xs.T @ err)
        client.push_dense_grad("w", grad.astype(np.float32))
        client.barrier(world=world)  # ... and all pushes land per step

    w_final = client.pull_dense("w")
    out = os.environ.get("PT_DIST_OUT")
    if out:
        with open(f"{out}.{tid}", "w") as f:
            json.dump({"trainer": tid, "losses": losses,
                       "w": w_final.ravel().tolist()}, f)
    os.makedirs(os.environ["PT_PS_DONE_DIR"], exist_ok=True)
    with open(os.path.join(os.environ["PT_PS_DONE_DIR"], str(tid)),
              "w") as f:
        f.write("done")
    client.close()


def main():
    if os.environ["PT_ROLE"] == "server":
        run_server()
    else:
        run_trainer()


if __name__ == "__main__":
    main()
