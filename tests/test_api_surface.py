"""Public API surface freeze.

Reference parity: paddle/fluid/API.spec — the reference freezes its
public API signatures so accidental removals/renames fail CI. Here the
freeze list is the load-bearing subset a reference user would reach
for; anything vanishing from it is a breaking change this test turns
into a loud failure."""

import importlib

import pytest

SURFACE = {
    "paddle_tpu": [
        "Tensor", "to_tensor", "Parameter", "seed", "set_flags",
        "get_flags", "save", "load", "no_grad", "grad", "Model",
        "DataParallel", "flops", "summary", "set_grad_enabled",
    ],
    "paddle_tpu.nn": [
        "Layer", "Linear", "Embedding", "Conv2D", "LayerNorm",
        "BatchNorm2D", "Transformer", "TransformerEncoder", "LSTM", "GRU",
        "MultiHeadAttention", "Sequential", "LayerList", "CrossEntropyLoss",
        "MSELoss", "Dropout", "ReLU", "GELU", "Softmax", "Pad2D", "Pad3D",
        "ZeroPad2D", "Unfold", "Fold", "MaxPool2D", "AdaptiveAvgPool2D",
        "functional", "initializer", "utils",
    ],
    "paddle_tpu.nn.functional": [
        "relu", "gelu", "softmax", "cross_entropy", "mse_loss", "linear",
        "embedding", "conv2d", "layer_norm", "dropout", "pad",
        "scaled_dot_product_attention", "ctc_loss", "one_hot",
    ],
    "paddle_tpu.nn.utils": [
        "weight_norm", "remove_weight_norm", "spectral_norm",
        "parameters_to_vector", "vector_to_parameters",
    ],
    "paddle_tpu.optimizer": [
        "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
        "Adagrad", "Adadelta", "RMSProp", "Lamb", "LarsMomentum",
        "DGCMomentum", "Ftrl", "Dpsgd", "DecayedAdagrad", "Rprop", "lr",
        "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
    ],
    "paddle_tpu.optimizer.lr": [
        "LRScheduler", "StepDecay", "MultiStepDecay", "ExponentialDecay",
        "CosineAnnealingDecay", "NoamDecay", "PolynomialDecay",
        "LinearWarmup", "ReduceOnPlateau",
    ],
    "paddle_tpu.distributed": [
        "init_parallel_env", "get_rank", "get_world_size", "all_reduce",
        "all_gather", "all_gather_object", "broadcast", "reduce_scatter",
        "alltoall", "barrier", "fleet", "DistributedStrategy",
        "DataParallel", "HybridCommunicateGroup", "UtilBase",
    ],
    "paddle_tpu.distributed.fleet": [
        "init", "distributed_optimizer", "distributed_model",
        "distributed_jit", "util", "worker_index", "worker_num",
    ],
    "paddle_tpu.io": [
        "Dataset", "IterableDataset", "TensorDataset", "DataLoader",
        "BatchSampler", "DistributedBatchSampler", "Sampler",
        "RandomSampler", "SequenceSampler",
    ],
    "paddle_tpu.static": [
        "InputSpec", "Program", "Executor", "build_program",
        "save_inference_model", "load_inference_model", "program_guard",
        "data",
    ],
    "paddle_tpu.jit": [
        "TrainStep", "EvalStep", "to_static", "save", "load",
    ],
    "paddle_tpu.amp": ["auto_cast", "GradScaler", "decorate"],
    "paddle_tpu.metric": ["Accuracy", "Precision", "Recall", "Auc"],
    "paddle_tpu.inference": ["Config", "Predictor", "create_predictor"],
    "paddle_tpu.vision": ["models", "transforms", "datasets"],
    "paddle_tpu.framework": [
        "save", "load", "MultiTrainer", "DistMultiTrainer",
        "TrainerFactory",
    ],
}


@pytest.mark.parametrize("module", sorted(SURFACE))
def test_api_surface_frozen(module):
    mod = importlib.import_module(module)
    missing = [n for n in SURFACE[module] if not hasattr(mod, n)]
    assert not missing, (f"{module} lost public API: {missing} — "
                        "update the freeze list ONLY for deliberate "
                        "breaking changes")


def test_namespace_modules():
    """paddle.fft / paddle.linalg are MODULES (reference layout), with
    the transforms inside them, autograd-aware."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import fft, linalg

    for n in ("fft", "ifft", "rfft", "irfft", "fft2", "fftshift",
              "fftfreq"):
        assert hasattr(fft, n), n
    for n in ("svd", "qr", "cholesky", "eigh", "det", "slogdet", "pinv",
              "matrix_power", "lu", "lu_unpack", "cdist"):
        assert hasattr(linalg, n), n
    # autograd flows through the namespace wrappers
    x = pt.to_tensor(np.ones(8, np.float32))
    x.stop_gradient = False
    y = fft.fft(x)
    (y.real() ** 2).sum().backward() if hasattr(y, "real") else None
