"""int8 execution-path tests.

Reference: slim/quantization/quantization_pass.py rewrites programs for
quantized inference and trt_int8_calibrator.cc feeds TensorRT int8
engines. TPU-native: PTQ calibration -> convert_to_int8 swaps
Linear/Conv2D for layers holding int8 weight buffers whose matmul/conv
execute as int8 x int8 -> int32 XLA ops (the MXU's native int8 path),
and the exported program serves through the AOT predictor.
"""

import os
import tempfile

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
import paddle_tpu.static as st
from paddle_tpu import inference
from paddle_tpu.jit import TrainStep
from paddle_tpu.quantization.quant import (PTQ, Int8Conv2D, Int8Linear,
                                           convert_to_int8,
                                           dequantize_int8, quantize_int8)
from paddle_tpu.vision.models import LeNet


@pytest.fixture(scope="module")
def trained_lenet():
    pt.seed(0)
    rng = np.random.default_rng(0)
    templates = rng.normal(size=(10, 1, 28, 28)).astype("float32")
    y = rng.integers(0, 10, size=512).astype("int64")
    x = (templates[y]
         + 0.3 * rng.normal(size=(512, 1, 28, 28))).astype("float32")
    model = LeNet()
    opt = optim.Adam(learning_rate=1e-3, parameters=model.parameters())
    step = TrainStep(model, opt, lambda m, b: pt.nn.functional
                     .cross_entropy(m(b[0]), b[1]).mean())
    for _ in range(60):
        step((x[:256], y[:256]))
    step.sync_to_model()
    model.eval()
    return model, x, y


@pytest.mark.slow
def test_int8_conversion_and_accuracy(trained_lenet):
    model, x, y = trained_lenet
    logits = model(pt.Tensor(jnp.asarray(x[256:])))
    acc_fp32 = float((np.asarray(logits.value).argmax(1)
                      == y[256:]).mean())
    assert acc_fp32 > 0.9  # the smoke model actually learned

    ptq = PTQ()
    ptq.calibrate(model, [(x[i * 32:(i + 1) * 32],) for i in range(8)],
                  num_batches=8)
    convert_to_int8(model, ptq)

    n_int8 = 0
    for _, sub in model.named_sublayers():
        if isinstance(sub, (Int8Linear, Int8Conv2D)):
            assert sub.weight_int8.value.dtype == jnp.int8
            n_int8 += 1
    assert n_int8 >= 3  # LeNet's convs + fcs now execute int8

    logits8 = model(pt.Tensor(jnp.asarray(x[256:])))
    acc_int8 = float((np.asarray(logits8.value).argmax(1)
                      == y[256:]).mean())
    assert acc_fp32 - acc_int8 <= 0.01, (acc_fp32, acc_int8)
    agree = float((np.asarray(logits8.value).argmax(1)
                   == np.asarray(logits.value).argmax(1)).mean())
    assert agree >= 0.98, agree

    # predictor serves the int8 program end-to-end
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "lenet_int8")
    st.save_inference_model(
        path, [st.InputSpec([32, 1, 28, 28], "float32")], layer=model)
    cfg = inference.Config(path)
    cfg.enable_low_precision("int8")
    pred = inference.Predictor(cfg)
    out = pred.run([x[256:288]])[0]
    np.testing.assert_allclose(out, np.asarray(logits8.value)[:32],
                               rtol=1e-4, atol=1e-4)


def test_quantize_dequantize_roundtrip():
    q = quantize_int8(pt.to_tensor(np.array([0.5, -1.0], "float32")), 1.0)
    assert q.dtype == jnp.int8
    d = dequantize_int8(q, 1.0)
    np.testing.assert_allclose(np.asarray(d), [0.5, -1.0], atol=1 / 127)


def test_int8_requires_calibration():
    from paddle_tpu.core.enforce import InvalidArgumentError
    model = LeNet()
    with pytest.raises(InvalidArgumentError, match="calibration"):
        convert_to_int8(model, PTQ())
