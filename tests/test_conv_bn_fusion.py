"""Conv+BN folding (the reference conv_bn_fuse_pass analog,
paddle/fluid/framework/ir/conv_bn_fuse_pass.h): eval-graph algebra that
removes every BatchNorm HBM pass from inference."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.inference import fuse_conv_bn


def _warm_stats(m, x, steps=3):
    m.train()
    for _ in range(steps):
        m(x)
    m.eval()


def test_fold_sequential_pair():
    pt.seed(0)
    m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8),
                      nn.ReLU())
    x = pt.to_tensor(np.random.default_rng(0).standard_normal(
        (2, 3, 8, 8)).astype(np.float32))
    _warm_stats(m, x)
    ref = m(x).numpy()
    assert fuse_conv_bn(m) == 1
    np.testing.assert_allclose(m(x).numpy(), ref, rtol=2e-5, atol=2e-5)
    assert not any(isinstance(s, nn.BatchNorm2D)
                   for s in m._sub_layers.values())


@pytest.mark.parametrize("family", ["resnet", "mobilenet_v2", "vgg_bn"])
def test_fold_model_zoo_parity(family):
    from paddle_tpu.vision.models import mobilenet_v2, resnet18, vgg11

    pt.seed(0)
    if family == "resnet":
        m = resnet18(num_classes=10)
    elif family == "mobilenet_v2":
        m = mobilenet_v2(scale=0.25, num_classes=10)
    else:
        m = vgg11(batch_norm=True, num_classes=0, with_pool=False)
    x = pt.to_tensor(np.random.default_rng(1).standard_normal(
        (2, 3, 32, 32)).astype(np.float32))
    _warm_stats(m, x)
    ref = m(x).numpy()
    n = fuse_conv_bn(m)
    assert n > 0, family
    np.testing.assert_allclose(m(x).numpy(), ref, rtol=5e-4, atol=5e-4,
                               err_msg=family)


def test_fold_refuses_train_mode():
    m = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
    m.train()
    with pytest.raises(RuntimeError):
        fuse_conv_bn(m)


def test_save_inference_model_folds_a_copy(tmp_path):
    """optimize=True folds on a copy: saved program output matches and
    the caller's model keeps its BatchNorms."""
    from paddle_tpu import static
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.vision.models import resnet18

    pt.seed(0)
    m = resnet18(num_classes=10)
    x = np.random.default_rng(2).standard_normal(
        (1, 3, 32, 32)).astype(np.float32)
    _warm_stats(m, pt.to_tensor(x))
    ref = m(pt.to_tensor(x)).numpy()

    prefix = str(tmp_path / "r18")
    static.save_inference_model(
        prefix, [static.InputSpec((1, 3, 32, 32), "float32", "x")],
        layer=m)
    # caller's model untouched
    assert any(isinstance(s, nn.BatchNorm2D) for s in
               (sub for _, sub in m.named_sublayers())), \
        "caller's model was mutated"
    cfg = Config(prefix)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    (out,) = pred.run([x])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=5e-4,
                               atol=5e-4)


def test_fold_skips_channel_mismatch():
    """A bn whose feature count differs from the conv's output channels
    (the pre-activation in!=out case) must not fold."""

    class PreAct(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bn1 = nn.BatchNorm2D(3)   # normalizes the INPUT
            self.conv1 = nn.Conv2D(3, 8, 3, padding=1)

        def forward(self, x):
            return self.conv1(pt.nn.functional.relu(self.bn1(x)))

    pt.seed(0)
    m = PreAct()
    x = pt.to_tensor(np.random.default_rng(3).standard_normal(
        (2, 3, 8, 8)).astype(np.float32))
    _warm_stats(m, x)
    ref = m(x).numpy()
    assert fuse_conv_bn(m) == 0  # channel guard refuses
    np.testing.assert_allclose(m(x).numpy(), ref, rtol=1e-6)


def test_save_inference_model_refuses_preact_misfold(tmp_path):
    """The equal-channel pre-activation block (bn BEFORE conv, same
    names the post-norm convention uses) cannot be distinguished
    structurally — save_inference_model must catch the wrong fold by
    numeric verification and export UNFUSED."""
    from paddle_tpu import static
    from paddle_tpu.inference import Config, create_predictor

    class PreActSame(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bn1 = nn.BatchNorm2D(8)   # normalizes the INPUT
            self.conv1 = nn.Conv2D(8, 8, 3, padding=1)  # in == out

        def forward(self, x):
            return self.conv1(pt.nn.functional.relu(self.bn1(x)))

    pt.seed(0)
    m = PreActSame()
    x = np.random.default_rng(4).standard_normal(
        (2, 8, 8, 8)).astype(np.float32)
    # train with a shifted input so running stats are far from identity
    _warm_stats(m, pt.to_tensor(x * 3.0 + 1.0))
    ref = m(pt.to_tensor(x)).numpy()

    prefix = str(tmp_path / "preact")
    with pytest.warns(UserWarning, match="UNFUSED"):
        static.save_inference_model(
            prefix, [static.InputSpec((2, 8, 8, 8), "float32", "x")],
            layer=m)
    cfg = Config(prefix)
    cfg.disable_gpu()
    pred = create_predictor(cfg)
    (out,) = pred.run([x])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=5e-4,
                               atol=5e-4)
