"""Fused decode hot path (r13): one-program engine step with fused
dequant–attention–sampling kernels.

The contracts this suite pins (ISSUE r13 acceptance):

- the FUSED engine's greedy output is BIT-IDENTICAL to the unfused
  (``fused_step=False``) engine across int8/fp KV pages, speculative
  on/off, chunked prefill on/off, and a 2-way serving mesh;
- the new fused kernels (`paged_attention_fused` epilogue,
  `fused_sample` streaming argmax) match their pure-JAX references in
  interpret mode, and the streaming sampler matches ``jnp.argmax``
  bit-for-bit including ties;
- decode-step traced-program op counts (the launch counter) are
  STRICTLY reduced under fusion;
- every fused exit path returns its pages (zero-leak audits);
- the conftest stray-serving guard detects but does NOT kill outside
  CI (the PR 7 tier-1 hazard's fix is detection-only by default).
"""

import functools
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference import SpeculativeConfig, create_decode_engine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.ops.pallas import flash_attention as fa
from paddle_tpu.ops.pallas import fused_sample as fs
from paddle_tpu.ops.pallas import paged_attention as pa

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests (see
    conftest.module_compile_cache)."""
    yield


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _engine(m, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq_len", 64)
    return create_decode_engine(m, **kw)


_PROMPTS = [(5,), (9,), (13,), (7,)]


def _prompts(vocab=1024):
    rng = np.random.default_rng(0)
    return [rng.integers(0, vocab, n).astype(np.int32)
            for (n,) in _PROMPTS]


def _run_stream(m, **kw):
    eng = _engine(m, **kw)
    rids = [eng.submit(p, max_new_tokens=8) for p in _prompts()]
    res = eng.run()
    eng.close()
    return [res[r].tolist() for r in rids], dict(eng.step_programs)


# ---------------------------------------------------------------------------
# Streaming sampler semantics (pure paths)
# ---------------------------------------------------------------------------

class TestFusedSampleSemantics:
    def test_streaming_argmax_bit_identical_odd_vocab(self, rng):
        for b, d, v, tile in [(4, 32, 1000, 256), (2, 16, 97, 32),
                              (3, 8, 5, 2048)]:
            hidden = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
            w = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
            got = fs.fused_sample(hidden, w, transpose_y=True, tile=tile)
            ref = jnp.argmax(hidden @ w.T, -1)
            assert (np.asarray(got) == np.asarray(ref)).all(), (b, d, v)

    def test_tie_breaks_to_first_index_like_argmax(self):
        # duplicate rows STRADDLING a tile boundary force exact ties
        hidden = jnp.ones((2, 4), jnp.float32)
        row = jnp.asarray([[1., 2., 3., 4.]], jnp.float32)
        w = jnp.concatenate([row * 0.5, row, row * 0.25, row, row],
                            axis=0)  # max tied at rows 1, 3, 4
        for tile in (2, 3, 5):
            got = fs.fused_sample(hidden, w, transpose_y=True, tile=tile)
            ref = jnp.argmax(hidden @ w.T, -1)
            assert (np.asarray(got) == np.asarray(ref)).all()
            assert (np.asarray(got) == 1).all()

    def test_feature_major_layout_and_bias(self, rng):
        hidden = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((16, 100)), jnp.float32)
        bias = jnp.asarray(rng.standard_normal((100,)), jnp.float32)
        got = fs.fused_sample(hidden, w, bias=bias, tile=32)
        ref = jnp.argmax(hidden @ w + bias, -1)
        assert (np.asarray(got) == np.asarray(ref)).all()

    def test_nan_logits_match_argmax_first_nan(self, rng):
        # a numerically-blown checkpoint must produce the SAME tokens
        # fused or unfused, or --no-fused-step bisection misattributes
        # the divergence to fusion: jnp.argmax returns the FIRST NaN
        # index, and the streaming carry must contaminate identically
        hidden = jnp.ones((2, 16), jnp.float32)
        w = jnp.asarray(rng.standard_normal((90, 16)), jnp.float32)
        for nan_rows in ((50,), (20, 70), (0,)):
            wn = w
            for r in nan_rows:
                wn = wn.at[r].set(jnp.nan)
            ref = jnp.argmax(hidden @ wn.T, -1)
            got = fs.fused_sample(hidden, wn, transpose_y=True, tile=32)
            assert (np.asarray(got) == np.asarray(ref)).all(), nan_rows
            assert (np.asarray(got) == min(nan_rows)).all()

    def test_topk_reservoir_matches_lax_topk(self, rng):
        hidden = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((500, 32)), jnp.float32)
        vals, idxs = fs.fused_sample(hidden, w, transpose_y=True,
                                     top_k=7, tile=64)
        fv, fi_ = jax.lax.top_k(hidden @ w.T, 7)
        np.testing.assert_allclose(np.asarray(vals), np.asarray(fv),
                                   rtol=1e-6)
        assert (np.asarray(idxs) == np.asarray(fi_)).all()

    def test_fused_sample_token_topk_draws_inside_topk(self, rng):
        from paddle_tpu.nn.decode import fused_sample_token
        hidden = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((300, 32)), jnp.float32)
        _, top_idx = jax.lax.top_k(hidden @ w.T, 5)
        key = jax.random.PRNGKey(0)
        for _ in range(5):
            tok, key = fused_sample_token(hidden, w, 0.8, 5, key,
                                          transpose_y=True, tile=64)
            for b in range(4):
                assert int(tok[b]) in set(np.asarray(top_idx[b]).tolist())

    def test_fused_verify_tokens_greedy_matches_unfused(self, rng):
        from paddle_tpu.nn.decode import (fused_verify_tokens,
                                          speculative_verify_tokens)
        b, s, d, v = 2, 4, 16, 200
        hidden = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((v, d)), jnp.float32)
        logits = hidden @ w.T
        drafts = jnp.asarray(rng.integers(0, v, (b, s - 1)), jnp.int32)
        a1, r1, f1, _ = fused_verify_tokens(hidden, drafts, w,
                                            transpose_y=True, tile=64)
        a2, r2, f2, _ = speculative_verify_tokens(logits, drafts)
        for x, y in ((a1, a2), (r1, r2), (f1, f2)):
            assert (np.asarray(x) == np.asarray(y)).all()


# ---------------------------------------------------------------------------
# Mosaic kernels vs references, interpret mode
# ---------------------------------------------------------------------------

class TestFusedKernelsInterpret:
    """The same harness TestPallasKernel uses on the CPU lane."""

    @pytest.fixture(autouse=True)
    def _interpret_mode(self, monkeypatch):
        for mod in (pa, fs):
            orig = mod.pl.pallas_call
            monkeypatch.setattr(mod.pl, "pallas_call",
                                functools.partial(orig, interpret=True))
        yield

    def test_fused_epilogue_matches_reference(self, rng):
        n_pages, page, h, d = 6, 8, 2, 64
        e = h * d
        kp = jnp.asarray(rng.standard_normal((n_pages, page, h, d)),
                         jnp.float32)
        vp = jnp.asarray(rng.standard_normal((n_pages, page, h, d)),
                         jnp.float32)
        table = jnp.asarray([[0, 2, 4], [5, 3, 1]], jnp.int32)
        lens = jnp.asarray([20, 7], jnp.int32)
        q = jnp.asarray(rng.standard_normal((2, 1, h, d)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((e, e)), jnp.float32)
        bias = jnp.asarray(rng.standard_normal((e,)), jnp.float32)
        with fa.force_flash_for_aot():
            assert pa.fused_epilogue_supported(q.shape, kp.shape,
                                               w.shape)
            out = pa.paged_attention_fused(q, kp, vp, table, lens, w,
                                           bias)
        ref = pa.paged_attention_fused_reference(q, kp, vp, table, lens,
                                                 w, bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_fused_epilogue_int8_pages(self, rng):
        from paddle_tpu.quantization.quant import quantize_kv
        n_pages, page, h, d = 5, 8, 2, 64
        e = h * d
        kq, ks = quantize_kv(jnp.asarray(
            rng.standard_normal((n_pages, page, h, d)), jnp.float32))
        vq, vs = quantize_kv(jnp.asarray(
            rng.standard_normal((n_pages, page, h, d)), jnp.float32))
        table = jnp.asarray([[1, 2, 3]], jnp.int32)
        lens = jnp.asarray([19], jnp.int32)
        q = jnp.asarray(rng.standard_normal((1, 1, h, d)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((e, e)), jnp.float32)
        with fa.force_flash_for_aot():
            out = pa.paged_attention_fused(q, kq, vq, table, lens, w,
                                           k_scale=ks, v_scale=vs)
        ref = pa.paged_attention_fused_reference(q, kq, vq, table, lens,
                                                 w, k_scale=ks,
                                                 v_scale=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_fused_argmax_kernel_matches_reference(self, rng):
        hidden = jnp.asarray(rng.standard_normal((4, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((1000, 128)), jnp.float32)
        ref = jnp.argmax(hidden @ w.T, -1)
        with fa.force_flash_for_aot():
            assert fs.fused_sample_supported(hidden.shape, w.shape)
            got = fs._fused_argmax_pallas(hidden, w, 0, None, 256)
            # feature-major layout streams natively (no transpose)
            got_fm = fs._fused_argmax_pallas(
                hidden, jnp.asarray(w.T), 1, None, 256)
        assert (np.asarray(got) == np.asarray(ref)).all()
        assert (np.asarray(got_fm) == np.asarray(ref)).all()

    def test_fused_argmax_kernel_nan_matches_argmax(self, rng):
        hidden = jnp.ones((2, 128), jnp.float32)
        w = jnp.asarray(rng.standard_normal((600, 128)), jnp.float32)
        w = w.at[300].set(jnp.nan)  # NaN row in the second tile
        ref = jnp.argmax(hidden @ w.T, -1)
        with fa.force_flash_for_aot():
            got = fs._fused_argmax_pallas(hidden, w, 0, None, 256)
        assert (np.asarray(got) == np.asarray(ref)).all()
        assert (np.asarray(got) == 300).all()

    def test_supported_gates(self):
        with fa.force_flash_for_aot():
            ok = pa.fused_epilogue_supported
            assert ok((4, 1, 2, 64), (10, 8, 2, 64), (128, 128))
            # projection rows must equal H*D
            assert not ok((4, 1, 2, 64), (10, 8, 2, 64), (256, 128))
            # E_out must lane-tile
            assert not ok((4, 1, 2, 64), (10, 8, 2, 64), (128, 100))
            # weight over the VMEM budget falls back (fp32)...
            assert not ok((4, 1, 16, 128), (10, 64, 16, 128),
                          (2048, 2048))
            # ...but the same head in bf16 storage fits the budget
            assert ok((4, 1, 16, 128), (10, 64, 16, 128),
                      (2048, 2048), w_itemsize=2)
        assert not pa.fused_epilogue_supported(
            (4, 1, 2, 64), (10, 8, 2, 64), (128, 128), backend="cpu")
        assert not fs.fused_sample_supported((4, 128), (100, 128),
                                             backend="cpu")


# ---------------------------------------------------------------------------
# Engine A/B: fused vs unfused bit-identity, program counts, leak audits
# ---------------------------------------------------------------------------

class TestFusedEngineParity:
    @pytest.mark.parametrize("kw", [
        {},
        {"kv_int8": True},
        {"speculative": "spec"},
        {"prefill_chunk_tokens": 8},
        {"speculative": "spec", "prefill_chunk_tokens": 8,
         "kv_int8": True},
    ], ids=["fp", "int8", "spec", "chunked", "spec_chunked_int8"])
    def test_fused_greedy_bit_identical(self, model, kw):
        kw = dict(kw)
        if kw.get("speculative") == "spec":
            kw["speculative"] = SpeculativeConfig(k=3)
        fused, _ = _run_stream(model, fused_step=True, **kw)
        if "speculative" in kw:
            kw["speculative"] = SpeculativeConfig(k=3)
        unfused, _ = _run_stream(model, fused_step=False, **kw)
        assert fused == unfused

    def test_mesh_two_way_bit_identical(self, model):
        from paddle_tpu.distributed.topology import make_serving_mesh
        mesh = make_serving_mesh(2)
        fused, _ = _run_stream(model, fused_step=True, mesh=mesh)
        unfused, _ = _run_stream(model, fused_step=False, mesh=mesh)
        single, _ = _run_stream(model, fused_step=True)
        assert fused == unfused == single

    def test_decode_programs_strictly_reduced(self, model):
        _, fused = _run_stream(model, fused_step=True)
        _, unfused = _run_stream(model, fused_step=False)
        assert fused["decode"] < unfused["decode"], (fused, unfused)
        assert fused["prefill"] < unfused["prefill"]

    def test_verify_programs_strictly_reduced(self, model):
        _, fused = _run_stream(model, fused_step=True,
                               speculative=SpeculativeConfig(k=3))
        _, unfused = _run_stream(model, fused_step=False,
                                 speculative=SpeculativeConfig(k=3))
        assert fused["verify"] < unfused["verify"], (fused, unfused)

    def test_generate_jit_paged_fused_matches_eager(self, model):
        # the jitted generate now samples through the streaming lm_head
        # and (paged) the fused attention epilogue; greedy tokens must
        # still match the eager debuggable reference exactly
        ids = np.asarray([[3, 1, 4, 1, 5]], np.int32)
        eager = model.generate(pt.Tensor(ids), max_new_tokens=6,
                               temperature=0.0)
        for kv in ("static", "paged", "paged_int8"):
            jitted = model.generate(pt.Tensor(ids), max_new_tokens=6,
                                    temperature=0.0, use_jit=True,
                                    kv_cache=kv, page_size=8)
            assert np.asarray(jitted.value).tolist() == \
                np.asarray(eager.value).tolist(), kv


class TestFusedLeakAudit:
    def test_close_midflight_returns_pages(self, model):
        for kw in ({}, {"speculative": SpeculativeConfig(k=3)},
                   {"prefill_chunk_tokens": 8}):
            eng = _engine(model, fused_step=True, **kw)
            for p in _prompts():
                eng.submit(p, max_new_tokens=8)
            for _ in range(3):
                eng.step()
            eng.close()  # asserts check_no_leak internally

    def test_deadline_eviction_returns_pages(self, model):
        eng = _engine(model, fused_step=True)
        eng.submit(_prompts()[0], max_new_tokens=8,
                   deadline_t=time.monotonic() + 0.2)
        deadline = time.monotonic() + 5
        while (eng.num_active or eng.num_queued) and \
                time.monotonic() < deadline:
            eng.step()
        eng.allocator.check_no_leak()
        eng.close()

    def test_drain_then_close_no_leak(self, model):
        eng = _engine(model, fused_step=True, kv_int8=True)
        for p in _prompts():
            eng.submit(p, max_new_tokens=4)
        eng.run()
        eng.allocator.check_no_leak()
        eng.close()


# ---------------------------------------------------------------------------
# Serving surface: recipe/escape hatch, health + gauge
# ---------------------------------------------------------------------------

class TestServingSurface:
    def test_server_health_reports_fused_and_programs(self, model):
        from paddle_tpu.serving import ServingServer, client_request
        srv = ServingServer(model, num_slots=2, page_size=8,
                            max_seq_len=64, prefix_cache=False)
        port = srv.start()
        try:
            rep = client_request("127.0.0.1", port, {
                "op": "generate", "prompt": [3, 1, 4, 1],
                "max_new_tokens": 4})
            assert "error" not in rep, rep
            h = client_request("127.0.0.1", port, {"op": "health"})
            assert h["fused_step"] is True
            assert h["step_programs"].get("decode", 0) > 0
            mx = client_request("127.0.0.1", port, {"op": "metrics"})
            assert "serving_step_programs" in mx["text"]
        finally:
            srv.stop()

    def test_engine_kwarg_escape_hatch_threads_through_recipe(self,
                                                              model):
        from paddle_tpu.serving import ServingServer
        srv = ServingServer(model, num_slots=2, page_size=8,
                            max_seq_len=64, prefix_cache=False,
                            fused_step=False)
        try:
            assert srv.engine.fused_step is False
            # the resurrection recipe rebuilds from the same kwargs
            assert srv._engine_kwargs.get("fused_step") is False
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Stray serving-process guard (the PR 7 tier-1 hazard's fix)
# ---------------------------------------------------------------------------

class TestServingGuard:
    def _spawn_marker(self):
        # argv carries the serving marker without running a server;
        # the child has THIS process as parent (ppid != 1), i.e. it
        # models a CONCURRENT run's live server
        return subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)",
             "paddle_tpu.serving.server"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def _spawn_orphan_marker(self):
        # double-fork: the intermediate exits immediately, so the
        # marker grandchild reparents to init (ppid 1) — the leaked-
        # from-a-dead-run shape the CI kill targets
        out = subprocess.run(
            [sys.executable, "-c",
             "import subprocess, sys\n"
             "p = subprocess.Popen([sys.executable, '-c',"
             " 'import time; time.sleep(60)',"
             " 'paddle_tpu.serving.server'],"
             " stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)\n"
             "print(p.pid)"],
            capture_output=True, text=True, timeout=30)
        return int(out.stdout.strip())

    @staticmethod
    def _alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    def test_guard_is_detection_only_outside_ci(self):
        import conftest
        proc = self._spawn_marker()
        try:
            time.sleep(0.2)
            found = conftest._handle_stray_serving(kill=False)
            assert proc.pid in [pid for pid, _, _, _ in found]
            assert proc.poll() is None, \
                "detection-only guard killed the process"
        finally:
            proc.kill()
            proc.wait()

    def test_guard_kills_only_orphans_in_ci_mode(self):
        import conftest
        live = self._spawn_marker()          # live parent: spared
        orphan = self._spawn_orphan_marker()  # ppid 1: reaped
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:  # wait for reparenting
                strays = {p: pp for p, pp, _ in
                          conftest._stray_serving_procs()}
                if strays.get(orphan) == 1:
                    break
                time.sleep(0.05)
            found = conftest._handle_stray_serving(kill=True)
            by_pid = {p: killed for p, _, _, killed in found}
            assert by_pid.get(orphan) is True, found
            assert by_pid.get(live.pid) is False, found
            assert live.poll() is None, \
                "CI guard killed a concurrent run's live server"
            deadline = time.monotonic() + 5
            while self._alive(orphan) and time.monotonic() < deadline:
                time.sleep(0.05)
            assert not self._alive(orphan)
        finally:
            live.kill()
            live.wait()
            if self._alive(orphan):
                os.kill(orphan, signal.SIGKILL)

    def test_guard_excludes_own_process_tree(self):
        import conftest
        own = conftest._proc_ancestors()
        assert os.getpid() in own
        assert os.getppid() in own
        assert os.getpid() not in [
            pid for pid, _, _ in conftest._stray_serving_procs()]
