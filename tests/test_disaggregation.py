"""Disaggregated prefill/decode serving (r20): prefill-class replicas
ship finished KV pages to decode replicas over the wire.

The contracts pinned here (ISSUE r20 acceptance):

- greedy outputs are BIT-IDENTICAL handoff-vs-local-prefill across
  the feature matrix (fp, paged_int8, chunked prefill, speculative,
  their combination, and a 2-way mesh), and ``role="mixed"`` is the
  pre-r20 replica (no default spill tier, no handoff accounting);
- every handoff failure — dead peer, typed peer error, corrupt blob,
  partial chain — is a COUNTED fallback to local prefill with the
  same greedy tokens, never a hang, and every new exit path leaves
  zero leaked pages on both sides;
- ``advertised_keys_info`` orders chain heads by the most recent
  touch anywhere in the chain and surfaces ``truncated`` so a capped
  advertisement cannot read as "not resident";
- the drain handoff (``handoff_chains`` / ``Supervisor.drain_replica``)
  ships a victim's chains to survivors by the same rendezvous the
  router steers with;
- the engine rejects ``max_seq_len`` beyond the model's position
  table TYPED (the silent-NaN corruption the r20 bench surfaced).
"""

import socket
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.inference import (PageAllocator, SpeculativeConfig,
                                  create_decode_engine)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (PrefixCache, ServingMetrics,
                                ServingServer, client_request)
from paddle_tpu.serving.metrics import merge_exports
from paddle_tpu.serving.prefix_cache import _block_hash, pack_page_blob
from paddle_tpu.serving.server import PageFetchFailed, fetch_page_blobs
from paddle_tpu.serving.supervisor import (FailoverRouter,
                                           handoff_chains,
                                           rendezvous_owner)


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests."""
    yield


def _model():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _model()


ENGINE_KW = dict(num_slots=2, page_size=8, max_seq_len=96)

# 19 tokens = 2 full shareable blocks at page_size 8: a handoff moves
# exactly 2 pages and chained prefill covers the 3-token suffix
PROMPT = list(range(3, 22))
MNT = 6


def _free_dead_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _reference(mode_kw, prompt=PROMPT, mnt=MNT):
    """Greedy tokens from a bare engine with the same config — the
    handoff runs must reproduce these bit-exactly."""
    eng = create_decode_engine(_model(), **ENGINE_KW, **mode_kw)
    try:
        rid = eng.submit(np.asarray(prompt, np.int32), mnt)
        return [int(t) for t in eng.run()[rid]][len(prompt):]
    finally:
        eng.close()


def _server(role, mode_kw=None, **kw):
    srv = ServingServer(
        _model(), role=role,
        metrics=ServingMetrics(registry=StatRegistry()),
        **{**ENGINE_KW, **(mode_kw or {}), **kw})
    srv.start()
    return srv


def _leak_ok(*srvs):
    for s in srvs:
        chk = client_request("127.0.0.1", s.port, {"op": "leak_check"})
        assert chk.get("ok"), chk


def _handoff_pair(mode_kw):
    """(prefill server, decode server) with identical weights/config."""
    return _server("prefill", mode_kw), _server("decode", mode_kw)


def _do_handoff(pf, dec, prompt=PROMPT, mnt=MNT, fetch_port=None):
    """Run the two-hop handoff by hand (what the role-aware router
    does): prefill_only on the prefill replica, then generate on the
    decode replica with a fetch_from hint naming it."""
    ack = client_request("127.0.0.1", pf.port,
                         {"op": "generate", "prompt": prompt,
                          "max_new_tokens": 1, "prefill_only": True},
                         timeout_s=120)
    assert ack.get("prefilled"), ack
    out = client_request(
        "127.0.0.1", dec.port,
        {"op": "generate", "prompt": prompt, "max_new_tokens": mnt,
         "fetch_from": {"host": "127.0.0.1",
                        "port": fetch_port or pf.port}},
        timeout_s=120)
    assert "error" not in out, out
    return ack, out


# ---------------------------------------------------------------------------
# advertised_keys_info: recency + truncation (satellite 1)
# ---------------------------------------------------------------------------

class TestAdvertisedKeys:
    def _cache_with_chains(self, n_chains, blocks=2, page=4):
        pc = PrefixCache(page)
        alloc = PageAllocator(4 * n_chains * blocks)
        chains = []
        for c in range(n_chains):
            prompt = np.asarray([100 * c + i
                                 for i in range(page * blocks + 1)],
                                np.int32)
            pages = alloc.alloc(("req", c), blocks + 1)
            row = np.array(pages, dtype=np.int32)
            keys = pc.insert(prompt, row, alloc, ("req", c), page, ())
            pc.release(keys)
            alloc.free(("req", c))
            chains.append((prompt, keys))
        return pc, alloc, chains

    def test_truncation_flag_and_cap(self):
        pc, _a, chains = self._cache_with_chains(6)
        info = pc.advertised_keys_info(limit=4)
        assert len(info["keys"]) == 4 and info["truncated"] is True
        info = pc.advertised_keys_info(limit=16)
        assert len(info["keys"]) == 6 and info["truncated"] is False
        # back-compat wrapper returns the bare list
        assert pc.advertised_keys(limit=16) == info["keys"]

    def test_deep_touch_refreshes_head_recency(self):
        """The r20 fix: traffic touching only a DEEP block of chain 0
        must keep chain 0's HEAD at the front of a truncated
        advertisement (the head entry's own last_used goes stale)."""
        pc, _a, chains = self._cache_with_chains(3)
        # whole-chain traffic on chains 1 then 2, then a DEEP-only
        # touch on chain 0 (what an insert() extending the chain, or a
        # partial re-acquire, does): chain 0's head entry keeps its old
        # tick, but the chain's RECENCY is its deepest touch
        for c in (1, 2):
            keys, _ = pc.match(chains[c][0])
            pc.acquire(keys)
            pc.release(keys)
        keys0, _ = pc.match(chains[0][0])
        pc.acquire(keys0[1:])  # leaf only: head last_used stays stale
        pc.release(keys0[1:])
        info = pc.advertised_keys_info(limit=1)
        assert info["truncated"] is True
        # pre-r20 ordering (head's own last_used) would advertise
        # chain 2 here and drop the hottest chain off the cap
        assert info["keys"] == [chains[0][1][0].hex()]


# ---------------------------------------------------------------------------
# Cache-level wire export/import
# ---------------------------------------------------------------------------

class _FakeIO:
    def __init__(self):
        self.spliced = {}

    def read_page(self, page):
        return [(np.full((4, 2, 3), page * 10 + l, np.float32),
                 np.full((4, 2, 3), page * 10 + l, np.float32),
                 None, None) for l in range(2)]

    def splice_page(self, pages, layers_list):
        for p, layers in zip(pages, layers_list):
            self.spliced[p] = float(layers[0][0].flat[0])


def _unit_cache(**kw):
    pc = PrefixCache(4, **kw)
    io = _FakeIO()
    pc.attach_device_io(io.read_page, io.splice_page)
    return pc, io


def _seed_chain(pc, alloc, prompt, owner="req"):
    n = pc._shareable_blocks(prompt)
    pages = alloc.alloc(owner, n + 1)
    row = np.array(pages, dtype=np.int32)
    keys = pc.insert(prompt, row, alloc, owner, pc.page_size, ())
    pc.release(keys)
    alloc.free(owner)
    return keys


class TestCacheWireOps:
    def test_chain_keys_are_pure_hashing(self):
        pc, _ = _unit_cache()
        prompt = np.arange(13, dtype=np.int32)
        keys = pc.chain_keys_for(prompt)
        assert len(keys) == 3  # (13-1)//4 full blocks
        # stateless: same prompt, same keys, no entries created
        assert pc.chain_keys_for(prompt) == keys
        assert not pc._entries

    def test_export_device_and_tier_blobs(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys = _seed_chain(pc, alloc, prompt)
        # spill one leaf; the rest stay device-resident
        assert pc.evict_until(alloc, alloc.free_count + 1)
        blobs, missing = pc.export_blobs(list(keys) + [b"\x00" * 8])
        assert set(blobs) == set(keys)
        assert missing == [b"\x00" * 8]
        assert pc.exported_pages == 3
        # every exported blob re-verifies (device pages were packed
        # fresh through pack_page_blob; tier blobs travel as stored)
        from paddle_tpu.serving.prefix_cache import unpack_page_blob
        for b in blobs.values():
            unpack_page_blob(b)

    def test_expand_heads_covers_device_and_spilled(self):
        pc, _io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys = _seed_chain(pc, alloc, prompt)
        # spill the whole chain (leaf-first)
        assert pc.evict_until(alloc, alloc.num_pages)
        assert set(pc.expand_heads([keys[0]])) == set(keys)
        # partially restore: device subtree + spilled members merge
        pc.restore_from_spill(prompt, (), alloc)
        assert set(pc.expand_heads([keys[0]])) == set(keys)

    def test_import_blobs_crc_and_skip(self):
        src, _ = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys = _seed_chain(src, alloc, prompt)
        src.evict_until(alloc, alloc.num_pages)
        blobs, _ = src.export_blobs(keys)

        dst, dio = _unit_cache(spill_bytes=1 << 20)
        bad = dict(blobs)
        k_corrupt = keys[1]
        bad[k_corrupt] = bad[k_corrupt][:-1] + \
            bytes([bad[k_corrupt][-1] ^ 0xFF])
        rep = dst.import_blobs(bad, heads=keys[:1])
        assert rep["imported"] == 2 and rep["corrupt"] == 1
        assert dst.import_corrupt == 1
        assert rep["bytes"] > 0
        # head advertised from the tier
        assert keys[0].hex() in dst.advertised_keys_info()["keys"]
        # re-import: tier-resident keys land again (inclusive tiers
        # overwrite identical content), device-resident keys skip
        dalloc = PageAllocator(8)
        rkeys, rpages, info = dst.restore_from_spill(prompt, (), dalloc)
        assert rkeys == keys[:1]  # corrupt k2 broke the chain walk
        assert info["fetched"] == 1  # wire-fetched split reported
        rep2 = dst.import_blobs(blobs)
        assert rep2["skipped"] == 1  # restored key now device-resident
        assert rep2["imported"] == 2

    def test_import_without_tiers_skips_all(self):
        dst, _ = _unit_cache()  # no spill tier configured
        rep = dst.import_blobs({b"k": b"blob"})
        assert rep == {"imported": 0, "corrupt": 0, "skipped": 1,
                       "dropped": 0, "bytes": 0}

    def test_import_blob_too_big_for_tier_counts_dropped(self):
        src, _ = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys = _seed_chain(src, alloc, prompt)
        src.evict_until(alloc, alloc.num_pages)
        blobs, _ = src.export_blobs(keys)
        # destination tier smaller than ONE blob: nothing can land —
        # the reply must say dropped, not imported (the drain-handoff
        # ack must never claim pages that are not resident), and the
        # dropped keys must not linger in the fetched-split record
        dst, _ = _unit_cache(spill_bytes=16)
        rep = dst.import_blobs(blobs, heads=keys[:1])
        assert rep["imported"] == 0 and rep["bytes"] == 0
        assert rep["dropped"] == len(blobs)
        assert dst.imported_pages == 0
        assert not dst._fetched_keys
        # the head never landed either: not advertised
        assert keys[0].hex() not in dst.advertised_keys_info()["keys"]


# ---------------------------------------------------------------------------
# fetch_pages / prefetch wire ops
# ---------------------------------------------------------------------------

class TestWireOps:
    def test_fetch_pages_roundtrip_and_missing(self, model):
        srv = _server("prefill")
        try:
            ack = client_request(
                "127.0.0.1", srv.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": 1, "prefill_only": True},
                timeout_s=120)
            assert ack.get("prefilled") and len(ack["keys"]) == 2
            blobs, missing, nbytes = fetch_page_blobs(
                "127.0.0.1", srv.port, keys=ack["keys"] + ["ab" * 8])
            assert len(blobs) == 2 and nbytes > 0
            assert missing == ["ab" * 8]
            # heads expand server-side to the full chain
            blobs2, _m, _b = fetch_page_blobs(
                "127.0.0.1", srv.port, heads=[ack["keys"][0]])
            assert set(blobs2) == set(blobs)
            _leak_ok(srv)
        finally:
            srv.stop()

    def test_fetch_pages_bad_request_and_dead_peer(self, model):
        srv = _server("mixed")
        try:
            r = client_request("127.0.0.1", srv.port,
                               {"op": "fetch_pages"})
            assert r["error"] == "BadRequest"
            r = client_request("127.0.0.1", srv.port,
                               {"op": "fetch_pages", "keys": ["zz"]})
            assert r["error"] == "BadRequest"
        finally:
            srv.stop()
        with pytest.raises(PageFetchFailed):
            fetch_page_blobs("127.0.0.1", _free_dead_port(),
                             keys=["ab" * 8], timeout_s=2.0)

    def test_prefetch_lands_peer_chain_in_tiers(self, model):
        pf, dec = _handoff_pair({})
        try:
            ack = client_request(
                "127.0.0.1", pf.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": 1, "prefill_only": True},
                timeout_s=120)
            rep = client_request(
                "127.0.0.1", dec.port,
                {"op": "prefetch", "host": "127.0.0.1",
                 "port": pf.port, "heads": [ack["keys"][0]]},
                timeout_s=120)
            assert rep.get("ok") and rep["imported"] == 2, rep
            assert rep["fetch_ms"] >= 0 and rep["missing"] == []
            # the prefetched chain is advertised and then SPLICED on
            # the next keyed generate — no fetch_from hint needed
            h = client_request("127.0.0.1", dec.port, {"op": "health"})
            assert ack["keys"][0] in h["prefix_keys"]
            ref = _reference({})
            out = client_request(
                "127.0.0.1", dec.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT}, timeout_s=120)
            assert out["generated"] == ref
            assert out["stats"]["restored_pages"] == 2
            assert out["stats"]["handoff_pages"] == 2
            _leak_ok(pf, dec)
        finally:
            pf.stop()
            dec.stop()

    def test_prefetch_typed_failures(self, model):
        dec = _server("decode")
        try:
            r = client_request("127.0.0.1", dec.port,
                               {"op": "prefetch", "heads": ["ab" * 8]})
            assert r["error"] == "BadRequest"  # no port
            r = client_request(
                "127.0.0.1", dec.port,
                {"op": "prefetch", "port": _free_dead_port(),
                 "heads": ["ab" * 8]}, timeout_s=120)
            assert r["error"] == "PageFetchFailed"
            assert dec.metrics.counter(
                "handoff_failures_total").get() == 1
        finally:
            dec.stop()


# ---------------------------------------------------------------------------
# Handoff-vs-local bit-identity across the feature matrix
# ---------------------------------------------------------------------------

MODES = {
    "fp": {},
    "int8": {"kv_int8": True},
    "chunked": {"prefill_chunk_tokens": 8},
    "spec": {"speculative": SpeculativeConfig(k=3)},
    "spec_int8_chunked": {"kv_int8": True,
                          "prefill_chunk_tokens": 8,
                          "speculative": SpeculativeConfig(k=3)},
}


class TestHandoffBitIdentity:
    @pytest.mark.parametrize("mode", sorted(MODES))
    def test_handoff_matches_local(self, mode):
        mode_kw = MODES[mode]
        ref = _reference(mode_kw)
        pf, dec = _handoff_pair(mode_kw)
        try:
            _ack, out = _do_handoff(pf, dec)
            assert out["generated"] == ref, mode
            st = out["stats"]
            assert st["handoff_pages"] == 2 and \
                st["restored_pages"] == 2, st
            assert st["handoff_ms"] > 0
            m = dec.metrics
            assert m.counter("handoff_pages_total").get() == 2
            assert m.counter("handoff_bytes_total").get() > 0
            assert m.counter("handoff_failures_total").get() == 0
            assert m.handoff_ms.snapshot()["count"] == 1
            assert "serving_handoff_ms_bucket" in m.prometheus_text()
            _leak_ok(pf, dec)
        finally:
            pf.stop()
            dec.stop()

    def test_handoff_matches_local_mesh2(self):
        from paddle_tpu.distributed.topology import make_serving_mesh
        mode_kw = {"mesh": make_serving_mesh(2)}
        ref = _reference(mode_kw)
        pf, dec = _handoff_pair(mode_kw)
        try:
            _ack, out = _do_handoff(pf, dec)
            assert out["generated"] == ref
            assert out["stats"]["handoff_pages"] == 2
            _leak_ok(pf, dec)
        finally:
            pf.stop()
            dec.stop()


# ---------------------------------------------------------------------------
# Handoff failure paths: counted typed fallbacks, zero leaks
# ---------------------------------------------------------------------------

class TestHandoffFallbacks:
    def test_dead_peer_falls_back_local(self, model):
        ref = _reference({})
        dec = _server("decode", handoff_timeout_s=2.0)
        try:
            out = client_request(
                "127.0.0.1", dec.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT,
                 "fetch_from": {"host": "127.0.0.1",
                                "port": _free_dead_port()}},
                timeout_s=120)
            assert out["generated"] == ref
            assert out["stats"]["handoff_pages"] == 0
            assert dec.metrics.counter(
                "handoff_failures_total").get() == 1
            _leak_ok(dec)
        finally:
            dec.stop()

    def test_corrupt_blobs_fall_back_local(self, model, monkeypatch):
        ref = _reference({})
        pf, dec = _handoff_pair({})
        try:
            import paddle_tpu.serving.server as server_mod
            real = server_mod.fetch_page_blobs

            def corrupting(*a, **kw):
                blobs, missing, nb = real(*a, **kw)
                return ({k: b[:-1] + bytes([b[-1] ^ 0xFF])
                         for k, b in blobs.items()}, missing, nb)

            monkeypatch.setattr(server_mod, "fetch_page_blobs",
                                corrupting)
            _ack, out = _do_handoff(pf, dec)
            assert out["generated"] == ref
            st = out["stats"]
            # nothing spliced from the wire; local prefill covered it
            assert st["handoff_pages"] == 0 and \
                st["restored_pages"] == 0
            assert dec.prefix_cache.import_corrupt == 2
            # all-corrupt import counts as a handoff failure
            assert dec.metrics.counter(
                "handoff_failures_total").get() == 1
            _leak_ok(pf, dec)
        finally:
            pf.stop()
            dec.stop()

    def test_partial_chain_splices_prefix(self, model, monkeypatch):
        """The peer delivers only the chain HEAD: restore splices what
        arrived and chained prefill covers the rest — bit-identical."""
        ref = _reference({})
        pf, dec = _handoff_pair({})
        try:
            import paddle_tpu.serving.server as server_mod
            real = server_mod.fetch_page_blobs

            def dropping(host, port, keys=None, heads=None, **kw):
                blobs, missing, nb = real(host, port, keys=keys,
                                          heads=heads, **kw)
                kept = dict(list(blobs.items())[:1])
                return kept, missing, sum(len(b) for b in kept.values())

            monkeypatch.setattr(server_mod, "fetch_page_blobs",
                                dropping)
            _ack, out = _do_handoff(pf, dec)
            assert out["generated"] == ref
            st = out["stats"]
            assert st["handoff_pages"] == 1 and \
                st["restored_pages"] == 1
            assert dec.metrics.counter(
                "handoff_failures_total").get() == 0
            _leak_ok(pf, dec)
        finally:
            pf.stop()
            dec.stop()

    def test_wrong_role_and_prefill_only_validation(self, model):
        pf = _server("prefill")
        try:
            r = client_request("127.0.0.1", pf.port,
                               {"op": "generate", "prompt": PROMPT,
                                "max_new_tokens": 4}, timeout_s=120)
            assert r["error"] == "WrongRole" and r["retryable"]
        finally:
            pf.stop()
        srv = ServingServer(model, prefix_cache=False,
                            metrics=ServingMetrics(
                                registry=StatRegistry()),
                            **ENGINE_KW)
        srv.start()
        try:
            r = client_request("127.0.0.1", srv.port,
                               {"op": "generate", "prompt": PROMPT,
                                "max_new_tokens": 1,
                                "prefill_only": True}, timeout_s=120)
            assert r["error"] == "BadRequest"
        finally:
            srv.stop()

    def test_bad_role_rejected_at_construction(self, model):
        with pytest.raises(ValueError, match="role"):
            ServingServer(model, role="verifier", **ENGINE_KW)


# ---------------------------------------------------------------------------
# role="mixed" is the pre-r20 replica
# ---------------------------------------------------------------------------

class TestMixedUnchanged:
    def test_no_default_tier_no_handoff_accounting(self, model):
        ref = _reference({})
        srv = _server("mixed")
        try:
            # no spill tier was defaulted (mixed = pre-r20 config)
            assert not srv.prefix_cache.tiers
            h = client_request("127.0.0.1", srv.port, {"op": "health"})
            assert h["role"] == "mixed"
            assert h["prefix_keys_truncated"] is False
            out = client_request(
                "127.0.0.1", srv.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT}, timeout_s=120)
            assert out["generated"] == ref
            # a fetch_from hint on a tier-less replica is ignored (no
            # failure counted — there is nowhere to land blobs)
            out = client_request(
                "127.0.0.1", srv.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT,
                 "fetch_from": {"port": _free_dead_port()}},
                timeout_s=120)
            assert out["generated"] == ref
            m = srv.metrics
            assert m.counter("handoff_pages_total").get() == 0
            assert m.counter("handoff_failures_total").get() == 0
            assert m.counter("handoff_bytes_total").get() == 0
            _leak_ok(srv)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Router role-aware dispatch (stub supervisor)
# ---------------------------------------------------------------------------

class _StubReplica:
    def __init__(self, idx, port=0, role="mixed", keys=(), load=0):
        self.idx = idx
        self.port = port
        self.role = role
        self.ready = True
        self.restarts = 0
        self.page_size = 8
        self.load = load
        self.prefix_keys = frozenset(keys)
        self.prefix_truncated = False

    def alive(self):
        return True


class _StubSup:
    def __init__(self, reps, host="127.0.0.1"):
        self.replicas = reps
        self.host = host

    def live(self):
        return [r for r in self.replicas if r.ready]


def _first_block_key(prompt, page_size=8):
    return _block_hash(None, np.asarray(prompt[:page_size],
                                        np.int32)).hex()


_NOTRACE = lambda ev, **kw: None  # noqa: E731


class TestRouterRoleDispatch:
    def test_pick_excludes_prefill_for_streams(self):
        reps = [_StubReplica(0, role="prefill"),
                _StubReplica(1, role="decode")]
        router = FailoverRouter(_StubSup(reps))
        for _ in range(4):
            assert router._pick(set(), exclude_prefill=True).idx == 1
        # prefill-only fleet: no decode-capable replica
        reps[1].ready = False
        assert router._pick(set(), exclude_prefill=True) is None

    def test_plan_handoff_decision_table(self):
        key = _first_block_key(PROMPT)
        msg = {"prompt": PROMPT, "key": "k"}
        # all-mixed fleet: no hint (pre-r20 routing byte-for-byte)
        router = FailoverRouter(_StubSup(
            [_StubReplica(0), _StubReplica(1)]))
        assert router._plan_handoff(msg, key, None, _NOTRACE) is None
        # chain already resident on a decode-capable replica: no hint
        reps = [_StubReplica(0, role="prefill", port=1),
                _StubReplica(1, role="decode", keys=[key])]
        router = FailoverRouter(_StubSup(reps))
        assert router._plan_handoff(msg, key, None, _NOTRACE) is None
        # a prefill replica advertises it: hint WITHOUT a prefill hop
        reps = [_StubReplica(0, role="prefill", port=7777, keys=[key]),
                _StubReplica(1, role="decode")]
        router = FailoverRouter(_StubSup(reps))
        hint = router._plan_handoff(msg, key, None, _NOTRACE)
        assert hint == {"host": "127.0.0.1", "port": 7777}
        assert router.handoffs_total == 1
        # disaggregate=False: no hint even with roles present
        router = FailoverRouter(_StubSup(reps), disaggregate=False)
        assert router.disaggregate is False

    def test_failed_prefill_hop_degrades_to_plain(self):
        key = _first_block_key(PROMPT)
        reps = [_StubReplica(0, role="prefill",
                             port=_free_dead_port()),
                _StubReplica(1, role="decode")]
        router = FailoverRouter(_StubSup(reps), backend_timeout_s=2.0)
        hint = router._plan_handoff({"prompt": PROMPT, "key": "k"},
                                    key, None, _NOTRACE)
        assert hint is None
        assert router.handoff_prefill_failures_total == 1

    def test_exhausted_budget_skips_prefill_hop(self):
        """A request whose deadline budget is already spent must not
        pay a prefill hop (the dispatch loop answers DeadlineExceeded
        from the SAME budget) — and a hopeless hop is not counted as
        a prefill failure."""
        key = _first_block_key(PROMPT)
        reps = [_StubReplica(0, role="prefill",
                             port=_free_dead_port()),
                _StubReplica(1, role="decode")]
        router = FailoverRouter(_StubSup(reps), backend_timeout_s=2.0)
        t0 = time.monotonic()
        hint = router._plan_handoff(
            {"prompt": PROMPT, "key": "k"}, key, None, _NOTRACE,
            budget_ms=50.0, arrival=time.monotonic() - 1.0)
        assert hint is None
        # no RPC was attempted: well under the 2 s backend timeout
        assert time.monotonic() - t0 < 1.0
        assert router.handoff_prefill_failures_total == 0

    def test_router_e2e_prefill_first_dispatch(self, model):
        """Live two-server fleet behind a real router socket: a keyed
        request routes prefill-first, the decode replica splices the
        fetched chain, greedy output matches the bare-engine
        reference."""
        ref = _reference({})
        pf, dec = _handoff_pair({})
        reps = [_StubReplica(0, port=pf.port, role="prefill"),
                _StubReplica(1, port=dec.port, role="decode")]
        router = FailoverRouter(_StubSup(reps))
        port = router.start()
        try:
            out = client_request(
                "127.0.0.1", port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT, "key": "k"}, timeout_s=120)
            assert out["generated"] == ref
            assert out["stats"]["handoff_pages"] == 2
            assert router.handoffs_total == 1
            assert router.handoff_prefill_failures_total == 0
            # the router's health op surfaces the accounting + roles
            st = client_request("127.0.0.1", port, {"op": "health"})
            assert st["handoffs_total"] == 1
            assert st["disaggregate"] is True
            roles = {r["idx"]: r["role"] for r in st["replicas"]}
            assert roles == {0: "prefill", 1: "decode"}
            _leak_ok(pf, dec)
        finally:
            router.stop()
            pf.stop()
            dec.stop()


# ---------------------------------------------------------------------------
# Drain handoff (ROADMAP 3(a) prefix-affinity-aware drain)
# ---------------------------------------------------------------------------

class TestDrainHandoff:
    def test_rendezvous_owner_stable(self):
        reps = [_StubReplica(i) for i in range(4)]
        owners = {}
        for i in range(16):
            key = _first_block_key(list(range(i, i + 20)))
            o1 = rendezvous_owner(key, reps).idx
            assert rendezvous_owner(key, reps).idx == o1
            owners.setdefault(o1, 0)
            owners[o1] += 1
        assert len(owners) >= 2  # spreads

    def test_handoff_chains_ships_to_survivors(self, model):
        """The drain path over live servers: the victim's advertised
        heads are prefetched by the survivor (rendezvous share), and a
        later keyed request on the survivor splices instead of
        re-prefilling."""
        ref = _reference({})
        victim = _server("mixed", spill_bytes=1 << 20)
        survivor = _server("mixed", spill_bytes=1 << 20)
        try:
            out = client_request(
                "127.0.0.1", victim.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT}, timeout_s=120)
            assert out["generated"] == ref
            heads = client_request("127.0.0.1", victim.port,
                                   {"op": "health"})["prefix_keys"]
            assert heads
            rep = handoff_chains(
                "127.0.0.1", victim.port, heads,
                [_StubReplica(1, port=survivor.port)])
            assert rep["failures"] == [], rep
            assert rep["imported_pages"] == 2 and rep["bytes"] > 0
            # victim drains clean; survivor serves from the handoff
            client_request("127.0.0.1", victim.port, {"op": "drain"})
            out = client_request(
                "127.0.0.1", survivor.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT}, timeout_s=120)
            assert out["generated"] == ref
            assert out["stats"]["restored_pages"] == 2
            assert out["stats"]["handoff_pages"] == 2
            _leak_ok(survivor)
        finally:
            victim.stop()
            survivor.stop()

    def test_handoff_chains_dead_survivor_recorded(self):
        rep = handoff_chains(
            "127.0.0.1", _free_dead_port(), ["ab" * 8],
            [_StubReplica(0, port=_free_dead_port())], timeout_s=2.0)
        assert rep["imported_pages"] == 0
        assert len(rep["failures"]) == 1

    @pytest.mark.slow
    def test_drain_replica_e2e_live_supervisor(self, tmp_path):
        """Supervisor.drain_replica on a LIVE 2-replica fleet: the
        victim's hot chain lands on the survivor through prefetch,
        the victim drains, and the survivor then serves the keyed
        prompt bit-identically from the spliced pages."""
        from paddle_tpu.serving.supervisor import Supervisor, _rpc
        env = {"JAX_PLATFORMS": "cpu", "TPU_SKIP_MDS_QUERY": "true",
               "PADDLE_TPU_COMPILE_CACHE": str(tmp_path / "cc")}
        sup = Supervisor(
            model="gpt_tiny", replicas=2,
            server_args=["--page-size", "8", "--max-seq-len", "96",
                         "--num-slots", "2", "--spill-mb", "16"],
            replica_env=env, probe_interval_s=0.3,
            backoff_base_s=3600)
        try:
            sup.start(wait_ready=True)
            v, s = sup.replicas
            out = client_request(
                "127.0.0.1", v.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT}, timeout_s=120)
            assert "error" not in out, out
            ref_tokens = out["generated"]
            # wait for the monitor to refresh the advertisement
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and not v.prefix_keys:
                time.sleep(0.2)
            assert v.prefix_keys
            rep = sup.drain_replica(0)
            assert rep["drained"], rep
            assert rep["handoff"]["imported_pages"] == 2, rep
            out = client_request(
                "127.0.0.1", s.port,
                {"op": "generate", "prompt": PROMPT,
                 "max_new_tokens": MNT}, timeout_s=120)
            assert out["generated"] == ref_tokens
            assert out["stats"]["handoff_pages"] == 2
            chk = _rpc("127.0.0.1", s.port, {"op": "leak_check"},
                       timeout_s=30.0)
            assert chk.get("ok"), chk
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Scheduler boost, trace split, fleet rollup, engine validation
# ---------------------------------------------------------------------------

class TestSatellites:
    def test_scheduler_handoff_boost(self):
        from paddle_tpu.inference.continuous_batching import \
            DecodeRequest
        from paddle_tpu.serving.scheduler import (Priority, SLOConfig,
                                                  SLOScheduler)
        now = time.monotonic()

        def req(handoff):
            r = DecodeRequest(0, np.asarray([1, 2], np.int32), 2,
                              priority=int(Priority.BATCH),
                              handoff=handoff)
            r.stats.submit_t = now
            return r

        sched = SLOScheduler(SLOConfig())
        assert sched.effective_priority(req(False), now) == \
            int(Priority.BATCH)
        assert sched.effective_priority(req(True), now) == \
            int(Priority.BATCH) + 1
        assert sched.explain(req(True), now)["handoff"] is True
        assert "handoff" not in sched.explain(req(False), now)
        # capped at INTERACTIVE; 0 restores the pre-r20 ordering
        big = SLOScheduler(SLOConfig(handoff_boost=99))
        assert big.effective_priority(req(True), now) == \
            int(Priority.INTERACTIVE)
        off = SLOScheduler(SLOConfig(handoff_boost=0))
        assert off.effective_priority(req(True), now) == \
            int(Priority.BATCH)

    def test_trace_reports_fetched_split(self, model):
        pf, dec = _handoff_pair({})
        dec.tracer.sample_rate = 1.0
        try:
            _do_handoff(pf, dec)
            tr = client_request("127.0.0.1", dec.port, {"op": "trace"})
            restores = [s for t in tr["traces"]
                        for s in t["spans"]
                        if s["name"] == "restore"]
            assert restores, tr["traces"]
            args = restores[-1].get("args", {})
            assert args.get("fetched") == 2
            assert args.get("pages") == 2
        finally:
            pf.stop()
            dec.stop()

    def test_fleet_rollup_merges_handoff_telemetry(self):
        mets = []
        for pages in (2, 3):
            m = ServingMetrics(registry=StatRegistry())
            m.counter("handoff_pages_total").add(pages)
            m.counter("handoff_bytes_total").add(100 * pages)
            m.handoff_ms.observe(float(pages))
            mets.append(m)
        exps = [m.export() for m in mets]
        for e in exps:
            assert "handoff_ms" in e["histograms"]
        merged = merge_exports([e["histograms"]["handoff_ms"]
                                for e in exps])
        assert merged["total"] == 2
        assert sum(e["counters"]["handoff_pages_total"]
                   for e in exps) == 5

    def test_engine_rejects_oversized_max_seq_len(self, model):
        """The r20 root-cause fix: positions past the model's wpe
        table read out-of-bounds embeddings whose NaNs poison the
        shared scratch page — construction must fail typed."""
        with pytest.raises(ValueError, match="position-embedding"):
            create_decode_engine(model, num_slots=2, page_size=8,
                                 max_seq_len=256)
        # at exactly the table size it builds fine
        eng = create_decode_engine(model, num_slots=2, page_size=8,
                                   max_seq_len=128)
        eng.close()
