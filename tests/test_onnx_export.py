"""ONNX export tests.

Reference: python/paddle/onnx/export.py:21 converts traced programs.
This image has no ``onnx`` package, so correctness is proven the hard
way: the exported bytes are parsed back with a generic protobuf reader
(paddle_tpu.onnx._proto.parse) and the graph is re-executed with a tiny
numpy interpreter of the emitted ONNX ops — outputs must match the eager
model.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.onnx import _proto as P
from paddle_tpu.onnx import export
from paddle_tpu.static import InputSpec

ONNX_DT = {P.DT_FLOAT: np.float32, P.DT_INT32: np.int32,
           P.DT_INT64: np.int64, P.DT_BOOL: np.bool_}


def _parse_tensor(data):
    msg = P.parse(data)
    dims = [v for _, v in msg.get(1, [])]
    dt = msg[2][0][1]
    name = msg[8][0][1].decode()
    raw = msg[9][0][1]
    return name, np.frombuffer(raw, ONNX_DT[dt]).reshape(dims)


def _parse_attr(data):
    msg = P.parse(data)
    name = msg[1][0][1].decode()
    at = msg[20][0][1]
    if at == P.AT_FLOAT:
        return name, msg[2][0][1]
    if at == P.AT_INT:
        return name, msg[3][0][1]
    if at == P.AT_STRING:
        return name, msg[4][0][1].decode()
    if at == P.AT_INTS:
        return name, [v for _, v in msg.get(8, [])]
    if at == P.AT_FLOATS:
        return name, [v for _, v in msg.get(7, [])]
    raise AssertionError(f"attr type {at}")


def _parse_model(data):
    model = P.parse(data)
    assert model[1][0][1] == 8  # ir_version
    g = P.parse(model[7][0][1])
    nodes = []
    for _, nd in g.get(1, []):
        n = P.parse(nd)
        nodes.append({
            "op": n[4][0][1].decode(),
            "inputs": [v.decode() for _, v in n.get(1, [])],
            "outputs": [v.decode() for _, v in n.get(2, [])],
            "attrs": dict(_parse_attr(a) for _, a in n.get(5, [])),
        })
    inits = dict(_parse_tensor(t) for _, t in g.get(5, []))
    def names(field):
        return [P.parse(vi)[1][0][1].decode()
                for _, vi in g.get(field, [])]
    return nodes, inits, names(11), names(12)


def _run_graph(nodes, env):
    """Tiny numpy interpreter for the op set the exporter emits."""
    for n in nodes:
        i = [env[x] for x in n["inputs"]]
        op, attrs = n["op"], n["attrs"]
        if op == "MatMul":
            out = i[0] @ i[1]
        elif op == "Add":
            out = i[0] + i[1]
        elif op == "Sub":
            out = i[0] - i[1]
        elif op == "Mul":
            out = i[0] * i[1]
        elif op == "Div":
            out = i[0] / i[1]
        elif op == "Max":
            out = np.maximum(i[0], i[1])
        elif op == "Tanh":
            out = np.tanh(i[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-i[0]))
        elif op == "Exp":
            out = np.exp(i[0])
        elif op == "Erf":
            from scipy.special import erf as _erf  # pragma: no cover
            out = _erf(i[0])
        elif op == "ReduceSum":
            out = i[0].sum(axis=tuple(i[1].tolist()))
        elif op == "ReduceMax":  # opset-13 signature: axes attribute
            out = i[0].max(axis=tuple(attrs["axes"]))
        elif op == "Reshape":
            out = i[0].reshape(i[1].tolist())
        elif op == "Transpose":
            out = i[0].transpose(attrs["perm"])
        elif op == "Expand":
            out = np.broadcast_to(i[0], i[1].tolist())
        elif op == "Identity":
            out = i[0]
        elif op == "Cast":
            out = i[0].astype(ONNX_DT[attrs["to"]])
        elif op == "Conv":
            out = _np_conv(i[0], i[1], i[2] if len(i) > 2 else None,
                           attrs)
        elif op == "Min":
            out = np.minimum(i[0], i[1])
        elif op == "Neg":
            out = -i[0]
        elif op == "Sqrt":
            out = np.sqrt(i[0])
        elif op == "Reciprocal":
            out = 1.0 / i[0]
        elif op == "Log":
            out = np.log(i[0])
        elif op == "Pow":
            out = i[0] ** i[1]
        elif op == "Squeeze":
            out = np.squeeze(i[0], axis=tuple(i[1].tolist()))
        elif op == "Einsum":
            out = np.einsum(attrs["equation"], *i)
        elif op == "Where":
            out = np.where(i[0], i[1], i[2])
        elif op == "Concat":
            out = np.concatenate(i, axis=attrs["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (v.tolist() for v in i[1:5])
            sl = [slice(None)] * i[0].ndim
            for s, e, a, st in zip(starts, ends, axes, steps):
                sl[a] = slice(s, e, st)
            out = i[0][tuple(sl)]
        elif op == "Gather":
            out = np.take(i[0], i[1], axis=attrs.get("axis", 0))
        elif op == "Equal":
            out = i[0] == i[1]
        elif op == "Less":
            out = i[0] < i[1]
        elif op == "Greater":
            out = i[0] > i[1]
        elif op == "LessOrEqual":
            out = i[0] <= i[1]
        elif op == "GreaterOrEqual":
            out = i[0] >= i[1]
        elif op == "Not":
            out = ~i[0]
        elif op == "And":
            out = i[0] & i[1]
        elif op == "Or":
            out = i[0] | i[1]
        else:
            raise AssertionError(f"interpreter: unexpected op {op}")
        env[n["outputs"][-1]] = out
        for extra in n["outputs"][:-1]:
            env[extra] = out
    return env


def _np_conv(x, w, b, attrs):
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("pads", [0, 0, 0, 0])
    x = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                   (pads[1], pads[3])))
    n, cin, h, wdt = x.shape
    cout, _, kh, kw = w.shape
    oh = (h - kh) // sh + 1
    ow = (wdt - kw) // sw + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    for oy in range(oh):
        for ox in range(ow):
            patch = x[:, :, oy * sh:oy * sh + kh, ox * sw:ox * sw + kw]
            out[:, :, oy, ox] = np.einsum("nchw,ochw->no", patch, w)
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        return F.softmax(self.fc2(F.relu(self.fc1(x))), axis=-1)


def test_export_mlp_matches_eager(tmp_path):
    import jax.numpy as jnp
    pt.seed(0)
    model = MLP()
    model.eval()
    path = export(model, str(tmp_path / "mlp"),
                  input_spec=[InputSpec([2, 8], "float32", "x")])
    data = open(path, "rb").read()
    nodes, inits, in_names, out_names = _parse_model(data)
    assert in_names == ["x"]
    assert {n["op"] for n in nodes} >= {"MatMul", "Add", "Max"}
    # weights exported byte-exact
    w1 = np.asarray(model.fc1.weight.value)
    assert any(np.array_equal(v, w1) for v in inits.values())

    x = np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32)
    env = dict(inits)
    env["x"] = x
    env = _run_graph(nodes, env)
    got = env[out_names[0]]
    ref = np.asarray(model(pt.Tensor(jnp.asarray(x))).value)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_export_conv_matches_eager(tmp_path):
    import jax.numpy as jnp

    class ConvNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(1, 3, 3, padding=1)
            self.fc = nn.Linear(3 * 6 * 6, 5)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            h = F.relu(self.conv(x))
            return self.fc(h.reshape((2, -1)))

    pt.seed(1)
    model = ConvNet()
    model.eval()
    path = export(model, str(tmp_path / "convnet"),
                  input_spec=[InputSpec([2, 1, 6, 6], "float32", "img")])
    nodes, inits, in_names, out_names = _parse_model(
        open(path, "rb").read())
    assert any(n["op"] == "Conv" for n in nodes)

    x = np.random.default_rng(1).normal(
        size=(2, 1, 6, 6)).astype(np.float32)
    env = dict(inits)
    env["img"] = x
    env = _run_graph(nodes, env)
    ref = np.asarray(model(pt.Tensor(jnp.asarray(x))).value)
    np.testing.assert_allclose(env[out_names[0]], ref, rtol=1e-4,
                               atol=1e-5)


def test_export_unsupported_is_explicit(tmp_path):
    class Pooled(nn.Layer):
        def __init__(self):
            super().__init__()

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return F.max_pool2d(x, 2)

    with pytest.raises(NotImplementedError, match="primitive"):
        export(Pooled(), str(tmp_path / "pool"),
               input_spec=[InputSpec([1, 1, 4, 4], "float32")])


def test_export_bert_encoder_matches_eager(tmp_path):
    """A real transformer: BERT-tiny embeddings (Gather), masked softmax
    attention (Einsum + Where), LayerNorm (Sqrt/Reciprocal), plus a
    slice+concat head — the r3 verdict's transformer-coverage gap.
    Round-tripped through the numpy ONNX interpreter against eager."""
    import jax.numpy as jnp

    import paddle_tpu.dispatch as dispatch
    from paddle_tpu.models.bert import BertModel, bert_tiny

    class BertHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bert = BertModel(bert_tiny())

        def forward(self, input_ids, attention_mask):
            seq, pooled = self.bert(input_ids,
                                    attention_mask=attention_mask)
            cls = seq[:, 0]  # Slice
            return dispatch.wrapped_ops["concat"]([cls, pooled], axis=-1)

    pt.seed(5)
    model = BertHead()
    model.eval()
    path = export(model, str(tmp_path / "bert"),
                  input_spec=[InputSpec([2, 16], "int32", "input_ids"),
                              InputSpec([2, 16], "int32",
                                        "attention_mask")])
    nodes, inits, in_names, out_names = _parse_model(
        open(path, "rb").read())
    ops = {n["op"] for n in nodes}
    assert {"Einsum", "Where", "Gather", "Concat", "Slice",
            "Sqrt"} <= ops, ops

    rng = np.random.default_rng(5)
    ids = rng.integers(0, 128, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), np.int32)
    mask[1, 10:] = 0  # ragged mask exercises the Where path for real
    env = dict(inits)
    env["input_ids"] = ids
    env["attention_mask"] = mask
    env = _run_graph(nodes, env)
    got = env[out_names[0]]
    ref = np.asarray(model(pt.Tensor(jnp.asarray(ids)),
                           pt.Tensor(jnp.asarray(mask))).value)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
