"""Communication-optimization strategies: DGC momentum, bf16-compressed
grad allreduce (fp16_allreduce), LocalSGD. Reference analogs:
meta_optimizers/{dgc,fp16_allreduce,localsgd}_optimizer.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
from paddle_tpu import nn
from paddle_tpu.distributed import (DistributedStrategy, fleet,
                                    LocalSGDTrainStep)


@pytest.fixture(scope="module", autouse=True)
def dp_env():
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 8}
    fleet.init(strategy=s)
    yield


class TinyMLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 1)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _batch(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    w = rng.randn(8, 1).astype(np.float32)
    y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y


def _mse(model, batch):
    x, y = batch
    pred = model(x)
    return ((pred - y) ** 2).mean()


# ------------------------------------------------------------------- DGC

def test_dgc_matches_momentum_during_warmup():
    pt.seed(0)
    params = {"w": jnp.asarray(np.random.RandomState(0)
                               .randn(4, 4).astype(np.float32))}
    grads = {"w": jnp.ones((4, 4), jnp.float32)}
    m = optim.Momentum(learning_rate=0.1, momentum=0.9)
    d = optim.DGCMomentum(learning_rate=0.1, momentum=0.9,
                          rampup_begin_step=100)
    ps_m, st_m = m.apply_gradients(params, grads, m.init(params))
    ps_d, st_d = d.apply_gradients(params, grads, d.init(params))
    np.testing.assert_allclose(ps_m["w"], ps_d["w"], rtol=1e-6)


def test_dgc_sparsifies_and_keeps_error_feedback():
    d = optim.DGCMomentum(learning_rate=0.1, momentum=0.9,
                          rampup_begin_step=0, sparsity=[0.75])
    params = {"w": jnp.zeros((64,), jnp.float32)}
    signs = jnp.where(jnp.arange(64) % 2 == 0, 1.0, -1.0)
    g = (jnp.arange(64, dtype=jnp.float32) + 1.0) * signs
    st = d.init(params)
    new_p, new_st = d.apply_gradients(params, {"w": g}, st)
    applied = (new_p["w"] != 0).sum()
    # ~25% of entries applied; the rest accumulated in v
    assert 4 <= int(applied) <= 32
    v = new_st["slots"]["w"]["v"]
    assert int((v != 0).sum()) == 64 - int(applied)
    # masked-out entries are preserved, not lost
    np.testing.assert_allclose(np.asarray(v[v != 0]),
                               np.asarray(g[np.asarray(new_p["w"]) == 0]),
                               rtol=1e-6)


def test_dgc_converges_on_quadratic():
    d = optim.DGCMomentum(learning_rate=0.01, momentum=0.9,
                          rampup_begin_step=0, sparsity=[0.9])
    target = jnp.asarray(np.random.RandomState(1)
                         .randn(32).astype(np.float32))
    params = {"w": jnp.zeros((32,), jnp.float32)}
    st = d.init(params)

    @jax.jit
    def step(p, s):
        g = jax.grad(lambda q: ((q["w"] - target) ** 2).sum())(p)
        return d.apply_gradients(p, g, s)

    for _ in range(300):
        params, st = step(params, st)
    err = float(((params["w"] - target) ** 2).mean())
    assert err < 1e-2, err


def test_strategy_dgc_swaps_optimizer():
    s = DistributedStrategy()
    s.dgc = True
    s.dgc_configs = {"rampup_begin_step": 5}
    wrapped = fleet.distributed_optimizer(
        optim.Momentum(learning_rate=0.1, momentum=0.9), s)
    assert isinstance(wrapped._inner, optim.DGCMomentum)
    assert wrapped._inner._rampup_begin == 5
    # non-momentum optimizers pass through untouched
    wrapped2 = fleet.distributed_optimizer(
        optim.Adam(learning_rate=0.1), s)
    assert isinstance(wrapped2._inner, optim.Adam)


def test_dgc_uniform_magnitudes_still_update():
    # ties at the quantile threshold must not starve the update
    d = optim.DGCMomentum(learning_rate=0.1, momentum=0.9,
                          rampup_begin_step=0, sparsity=[0.999])
    params = {"b": jnp.zeros((4,), jnp.float32),
              "s": jnp.zeros((1,), jnp.float32)}
    g = {"b": jnp.ones((4,), jnp.float32),
         "s": jnp.ones((1,), jnp.float32)}
    st = d.init(params)
    p, st = d.apply_gradients(params, g, st)
    assert float(jnp.abs(p["b"]).max()) > 0, "uniform grads starved"
    assert float(jnp.abs(p["s"]).max()) > 0, "size-1 tensor starved"


def test_strategy_localsgd_routes_distributed_jit():
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 8}
    s.localsgd = True
    s.localsgd_configs = {"k_steps": 2}
    try:
        fleet.init(strategy=s)
        pt.seed(0)
        step = fleet.distributed_jit(
            TinyMLP(), optim.SGD(learning_rate=0.05), _mse, strategy=s)
        assert isinstance(step, LocalSGDTrainStep)
        assert step.k_steps == 2
        x, y = _batch(64)
        first = float(step((x, y)))
        for _ in range(10):
            last = float(step((x, y)))
        assert last < first
    finally:
        fleet.init(strategy=DistributedStrategy())


def test_localsgd_warmup_syncs_every_step():
    # before begin_step training is fully synchronous: replica params
    # must stay identical even though k_steps would allow divergence
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 8}
    try:
        fleet.init(strategy=s)
        pt.seed(0)
        step = LocalSGDTrainStep(
            TinyMLP(), optim.SGD(learning_rate=0.05), _mse,
            k_steps=4, begin_step=100)
        x, y = _batch(64)
        step((x, y))
        step((x, y))
        for v in jax.tree_util.tree_leaves(step.params):
            v = np.asarray(v)
            assert np.allclose(v, v[:1]), "replicas diverged in warmup"
    finally:
        fleet.init(strategy=DistributedStrategy())


def test_localsgd_scalar_batch_leaf():
    # 0-d batch leaves must be replicated, not dp-sharded
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 8}
    try:
        fleet.init(strategy=s)
        pt.seed(0)
        step = LocalSGDTrainStep(
            TinyMLP(), optim.SGD(learning_rate=0.05),
            lambda m, b: _mse(m, (b[0], b[1])) * b[2], k_steps=2)
        x, y = _batch(64)
        loss = step((x, y, np.float32(0.5)))
        assert np.isfinite(float(loss))
    finally:
        fleet.init(strategy=DistributedStrategy())


# --------------------------------------------------- bf16 grad allreduce

def test_fp16_allreduce_step_matches_exact_path():
    x, y = _batch()

    def run(compress):
        pt.seed(0)
        model = TinyMLP()
        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 8}
        s.fp16_allreduce = compress
        step = fleet.distributed_jit(
            model, optim.SGD(learning_rate=0.1), _mse,
            strategy=s, seed=0)
        losses = [float(step((x, y))) for _ in range(5)]
        return losses

    exact = run(False)
    comp = run(True)
    assert comp[-1] < comp[0], comp
    # bf16 mantissa (8 bits) → losses track within ~1%
    np.testing.assert_allclose(comp, exact, rtol=2e-2)


def test_fp16_allreduce_rejects_mp():
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    s.fp16_allreduce = True
    fleet.init(strategy=s)
    try:
        with pytest.raises(ValueError, match="fp16_allreduce"):
            fleet.distributed_jit(TinyMLP(), optim.SGD(0.1), _mse,
                                  strategy=s)
    finally:
        s2 = DistributedStrategy()
        s2.hybrid_configs = {"dp_degree": 8}
        fleet.init(strategy=s2)


# -------------------------------------------------------------- LocalSGD

def test_localsgd_replicas_diverge_then_sync():
    pt.seed(0)
    model = TinyMLP()
    step = LocalSGDTrainStep(model, optim.SGD(learning_rate=0.05),
                             _mse, k_steps=4, begin_step=1, seed=0)
    x, y = _batch(64)
    losses = [step((x, y)) for _ in range(3)]  # 3 local steps, no sync yet
    w = np.asarray(step.params["fc1.weight"])
    spread = np.abs(w - w[0]).max()
    assert spread > 0, "replicas should diverge between syncs"
    step((x, y))  # 4th step triggers sync
    w = np.asarray(step.params["fc1.weight"])
    np.testing.assert_allclose(w, np.broadcast_to(w[0], w.shape),
                               atol=1e-6)
    assert losses[-1] < losses[0] * 1.5


def test_localsgd_trains():
    pt.seed(0)
    model = TinyMLP()
    step = LocalSGDTrainStep(model, optim.SGD(learning_rate=0.05),
                             _mse, k_steps=2, seed=0)
    x, y = _batch(64)
    first = step((x, y))
    for _ in range(30):
        last = step((x, y))
    assert last < first * 0.7, (first, last)
    step.sync_to_model()  # writes averaged params back into the Layer
    out = model(pt.Tensor(jnp.asarray(x)))
    assert np.isfinite(np.asarray(out.value)).all()
