"""Heter-lite: a host-resident embedding (bigger than a synthetic HBM
cap) trains inside a jitted step with loss parity vs an in-HBM baseline.

Reference capability being matched: heter-PS's host-side giant sparse
tables feeding the accelerator step (service/heter_client.cc:1,
framework/fleet/heter_ps/hashtable.h:1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
from paddle_tpu import nn
from paddle_tpu.distributed.heter import DenseHostTable, HostEmbedding
from paddle_tpu.jit import TrainStep

VOCAB, DIM, CLASSES = 5000, 16, 7


class _Cls(nn.Layer):
    def __init__(self, emb):
        super().__init__()
        self.emb = emb
        self.fc = nn.Linear(DIM, CLASSES)

    def forward(self, ids, labels=None):
        import paddle_tpu.dispatch as dispatch
        F = dispatch.wrapped_ops
        h = F["mean"](self.emb(ids), axis=1)
        logits = self.fc(h)
        if labels is None:
            return logits
        return F["mean"](F["cross_entropy"](logits, labels))


def _batches(n=6, b=8, s=12, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, VOCAB, (b, s)).astype(np.int32),
             rng.integers(0, CLASSES, (b,)).astype(np.int64))
            for _ in range(n)]


def _make_models(lr):
    pt.seed(0)
    host = _Cls(HostEmbedding(VOCAB, DIM, lr=lr, update="sgd", seed=3))
    pt.seed(0)
    dense = _Cls(nn.Embedding(VOCAB, DIM))
    # identical initial state (Embedding's init consumes RNG draws the
    # HostEmbedding doesn't, shifting fc's init — copy everything)
    # .copy(): on the CPU backend jnp.asarray can zero-copy ALIAS the
    # numpy buffer, and the host-side push mutates that buffer in place
    dense.emb.weight.value = jnp.array(host.emb.table.weight.copy())
    # fresh copies: TrainStep donates its state buffers, so sharing the
    # same jax arrays across the two models would alias donated memory
    dense.fc.weight.value = jnp.array(np.asarray(host.fc.weight.value))
    dense.fc.bias.value = jnp.array(np.asarray(host.fc.bias.value))
    return host, dense


def test_host_embedding_loss_parity_vs_in_hbm():
    lr = 0.1
    host, dense = _make_models(lr)
    hs = TrainStep(host, optim.SGD(learning_rate=lr),
                   lambda m, b: m(b[0], labels=b[1]))
    ds = TrainStep(dense, optim.SGD(learning_rate=lr),
                   lambda m, b: m(b[0], labels=b[1]))
    hl, dl = [], []
    for batch in _batches():
        hl.append(float(hs(batch)))
        jax.effects_barrier()  # strict read-after-write for parity
        dl.append(float(ds(batch)))
    # f32 reassociation on duplicate ids (np.subtract.at is sequential,
    # the device scatter-add is tree-ordered) allows ~1e-5 drift
    np.testing.assert_allclose(hl, dl, rtol=1e-4, atol=1e-6)
    # actually learning: repeated steps on one fixed batch descend
    fixed = _batches(n=1, seed=9)[0]
    fixed_losses = []
    for _ in range(5):
        fixed_losses.append(float(hs(fixed)))
        jax.effects_barrier()
    assert fixed_losses[-1] < fixed_losses[0], fixed_losses
    # and the host table moved (it IS being trained)
    fresh = DenseHostTable(VOCAB, DIM, lr=lr, seed=3)
    assert not np.array_equal(host.emb.table.weight, fresh.weight)


def test_table_exceeds_cap_but_device_holds_rows_only():
    """Synthetic HBM cap: the table is bigger than the cap, yet the
    compiled step's device arguments stay under it — only looked-up rows
    travel."""
    cap = 8 << 20  # 8 MiB synthetic HBM budget for model state
    table = DenseHostTable(200_000, 64, lr=0.1)  # 51 MiB >> cap
    assert table.nbytes > 6 * cap
    pt.seed(0)
    model = _ClsBig(table)
    step = TrainStep(model, optim.SGD(learning_rate=0.1),
                     lambda m, b: m(b[0], labels=b[1]))
    rng = np.random.default_rng(1)
    batch = (rng.integers(0, 200_000, (4, 16)).astype(np.int32),
             rng.integers(0, CLASSES, (4,)).astype(np.int64))
    l0 = float(step(batch))
    l1 = float(step(batch))
    assert np.isfinite(l0) and l1 < l0
    # device-side state (params + opt slots): everything the step holds
    args_bytes = sum(
        int(np.prod(v.shape)) * v.dtype.itemsize
        for v in jax.tree_util.tree_leaves((step.params, step.opt_state)))
    assert args_bytes < cap, args_bytes


class _ClsBig(nn.Layer):
    def __init__(self, table):
        super().__init__()
        self.emb = HostEmbedding(200_000, 64, table=table)
        self.fc = nn.Linear(64, CLASSES)

    def forward(self, ids, labels=None):
        import paddle_tpu.dispatch as dispatch
        F = dispatch.wrapped_ops
        h = F["mean"](self.emb(ids), axis=1)
        logits = self.fc(h)
        if labels is None:
            return logits
        return F["mean"](F["cross_entropy"](logits, labels))


def test_prefetch_overlap_same_result():
    lr = 0.05
    host, dense = _make_models(lr)
    hs = TrainStep(host, optim.SGD(learning_rate=lr),
                   lambda m, b: m(b[0], labels=b[1]))
    ds = TrainStep(dense, optim.SGD(learning_rate=lr),
                   lambda m, b: m(b[0], labels=b[1]))
    batches = _batches(seed=5)
    hl, dl = [], []
    for i, batch in enumerate(batches):
        if i + 1 < len(batches):
            host.emb.prefetch(batches[i + 1][0])  # warm next batch
        hl.append(float(hs(batch)))
        jax.effects_barrier()  # strict parity mode (see heter.py docs)
        dl.append(float(ds(batch)))
    np.testing.assert_allclose(hl, dl, rtol=1e-4, atol=1e-6)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_host_embedding_under_data_parallel_mesh():
    """The fleet path: host table + dp-sharded batch in one GSPMD step."""
    from paddle_tpu.distributed import DistributedStrategy, fleet

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 8}
    fleet.init(strategy=s)
    lr = 0.1
    pt.seed(0)
    host = _Cls(HostEmbedding(VOCAB, DIM, lr=lr, update="sgd", seed=3))
    step = fleet.distributed_jit(host, optim.SGD(learning_rate=lr),
                                 lambda m, b: m(b[0], labels=b[1]))
    rng = np.random.default_rng(2)
    batch = (rng.integers(0, VOCAB, (16, 12)).astype(np.int32),
             rng.integers(0, CLASSES, (16,)).astype(np.int64))
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_host_embedding_survives_bf16_cast():
    """model.to(bfloat16) casts the anchor param; the lookup's custom
    vjp must return a matching-dtype cotangent (the bf16 recipe every
    TPU bench uses)."""
    pt.seed(0)
    model = _Cls(HostEmbedding(VOCAB, DIM, lr=0.1, seed=3))
    model.to(dtype="bfloat16")
    step = TrainStep(model, optim.SGD(learning_rate=0.1),
                     lambda m, b: m(b[0], labels=b[1]))
    batch = _batches(n=1, seed=11)[0]
    l0 = float(step(batch))
    jax.effects_barrier()
    l1 = float(step(batch))
    assert np.isfinite(l0) and np.isfinite(l1)


class _DenseNet(nn.Layer):
    """Dense stage for the split-brain pipeline: consumes the sparse
    stage's concatenated per-slot embeddings."""

    def __init__(self, n_slots, dim=DIM):
        super().__init__()
        self.fc = nn.Linear(n_slots * dim, CLASSES)

    def forward(self, acts, labels=None):
        import paddle_tpu.dispatch as dispatch
        F = dispatch.wrapped_ops
        logits = self.fc(acts)
        if labels is None:
            return logits
        return F["mean"](F["cross_entropy"](logits, labels))


class _MonoNet(nn.Layer):
    """Monolithic twin: device Embedding + the same dense head, with
    the concat layout matching the sparse stage."""

    def __init__(self, n_slots, dim=DIM):
        super().__init__()
        self.emb = nn.Embedding(VOCAB, dim)
        self.fc = nn.Linear(n_slots * dim, CLASSES)

    def forward(self, ids, labels=None):
        import paddle_tpu.dispatch as dispatch
        F = dispatch.wrapped_ops
        b, s = ids.shape[0], ids.shape[1]
        h = F["reshape"](self.emb(ids), (b, s * DIM))
        logits = self.fc(h)
        if labels is None:
            return logits
        return F["mean"](F["cross_entropy"](logits, labels))


def test_heter_pipeline_split_brain_loss_parity():
    """HeterPipelineTrainer (CPU worker pool sparse stage + jitted
    dense stage, reference heter_client.cc orchestration): sync mode
    must match a monolithic in-HBM model step for step; async mode must
    still learn."""
    from paddle_tpu.distributed.heter import HeterPipelineTrainer

    n_slots, lr = 12, 0.1
    table = DenseHostTable(VOCAB, DIM, lr=lr, update="sgd", seed=3)
    pt.seed(0)
    dense = _DenseNet(n_slots)
    trainer = HeterPipelineTrainer(table, DIM, dense,
                                   optim.SGD(learning_rate=lr),
                                   lambda m, a, l: m(a, labels=l))
    pt.seed(0)
    mono = _MonoNet(n_slots)
    mono.emb.weight.value = jnp.array(table.weight.copy())
    mono.fc.weight.value = jnp.array(np.asarray(dense.fc.weight.value))
    mono.fc.bias.value = jnp.array(np.asarray(dense.fc.bias.value))
    mstep = TrainStep(mono, optim.SGD(learning_rate=lr),
                      lambda m, b: m(b[0], labels=b[1]))

    batches = _batches(n=5, seed=21)
    heter_losses = trainer.run(batches, sync=True)
    mono_losses = [float(mstep(b)) for b in batches]
    # f32 reassociation on duplicate ids within a batch (host scatter is
    # sequential, device scatter-add tree-ordered): tiny drift allowed
    np.testing.assert_allclose(heter_losses, mono_losses, rtol=1e-4,
                               atol=1e-6)

    # async pipeline mode: bounded-staleness updates still descend on a
    # fixed batch replayed (prefetch + push overlap exercised)
    table2 = DenseHostTable(VOCAB, DIM, lr=lr, update="sgd", seed=3)
    pt.seed(0)
    dense2 = _DenseNet(n_slots)
    trainer2 = HeterPipelineTrainer(table2, DIM, dense2,
                                    optim.SGD(learning_rate=lr),
                                    lambda m, a, l: m(a, labels=l))
    fixed = _batches(n=1, seed=23)[0]
    async_losses = trainer2.run([fixed] * 6, sync=False)
    assert np.isfinite(async_losses).all()
    assert async_losses[-1] < async_losses[0], async_losses


def test_heter_pipeline_over_ps_sparse_table():
    """The split-brain trainer over the PS-core SparseTable (rows
    created on first access — the trillion-parameter pattern,
    common_sparse_table.cc): learns, and only touched rows
    materialize."""
    from paddle_tpu.distributed.heter import HeterPipelineTrainer
    from paddle_tpu.distributed.ps import SparseTable

    n_slots = 8
    table = SparseTable(emb_dim=DIM, lr=0.1)
    pt.seed(0)
    dense = _DenseNet(n_slots)
    trainer = HeterPipelineTrainer(table, DIM, dense,
                                   optim.SGD(learning_rate=0.1),
                                   lambda m, a, l: m(a, labels=l))
    rng = np.random.default_rng(31)
    ids = rng.integers(0, 10_000_000, (8, n_slots)).astype(np.int64)
    labels = rng.integers(0, CLASSES, (8,)).astype(np.int64)
    losses = trainer.run([(ids, labels)] * 6, sync=True)
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    # lazy materialization: only the ids actually touched have rows,
    # out of a 10M-key space
    assert len(table.rows) == len(np.unique(ids))
    trainer.shutdown()
