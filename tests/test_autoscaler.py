"""Autoscaling actuator (r21): crash-safe fleet journal, recovery
planning, the closed-loop scale/shape actuator, and its guard rails.

The contracts pinned here (ISSUE r21 acceptance):

- the `FleetJournal` is atomic + crc-checked (tmp/rename/fsync — the
  ResilientCheckpointManager discipline): a reader either sees the
  previous committed state or the new one, never a torn file, and
  tools/flight_inspect.py lints the same bytes without importing
  paddle_tpu;
- `plan_recovery` is a PURE function a restarted supervisor obeys:
  adopt live replicas, respawn dead ones, resolve every half-finished
  action (adopt-or-reap an orphaned spawn, resume-or-re-admit a
  half-drained victim, finish a rerole as respawn-with-new-role) and
  never double-spawn;
- scale-down refuses TYPED when the survivor set would be empty,
  below the min envelope, or lose the last replica of a role;
- a successful ready probe RESETS the exponential-backoff state
  (satellite fix: one past crash loop must not penalise the next
  legitimate respawn);
- rendezvous ownership moves MINIMALLY under churn: scaling up moves
  only the keys the new replica now owns, scaling down only the
  victim's keys — the property the drain-handoff and router affinity
  both stand on;
- the shape rule (`desired_prefill` + `plan_shape`) is the README
  prefill:decode tuning guidance, executable.

Integration (slow lane): a live autoscaled fleet keeps keyed greedy
outputs BIT-IDENTICAL across scale events, and chaos INVARIANT 7
(tools/chaos_serving.py --autoscale-chaos) holds: SIGKILL the
supervisor mid-spawn and mid-scale-down, restart it from the journal
— no stranded processes, no lost chains, zero leaks, typed
termination everywhere.
"""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

import pytest

from paddle_tpu.serving.autoscaler import (AutoscaleConfig, Autoscaler,
                                           FleetJournal, desired_prefill,
                                           load_journal, open_actions,
                                           plan_recovery,
                                           scan_marked_replicas)
from paddle_tpu.serving.supervisor import (Replica, Supervisor,
                                           rendezvous_owner)

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    # sys.modules registration: dataclasses in the tool resolve their
    # (future-import) string annotations through sys.modules
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _sup(n=2, roles=None, tmp=None, **kw):
    """A Supervisor record set WITHOUT processes: construction never
    spawns (start() does), so guard/plan logic is unit-testable."""
    kw.setdefault("collect_metrics", False)
    sup = Supervisor(model="gpt_tiny", replicas=n, roles=roles,
                     log_dir=str(tmp) if tmp else None, **kw)
    return sup


# ---------------------------------------------------------------------------
# FleetJournal: atomic, crc-checked, bounded, lint-clean
# ---------------------------------------------------------------------------

class TestFleetJournal:
    def test_begin_before_action_then_commit_roundtrip(self, tmp_path):
        path = str(tmp_path / "j.json")
        j = FleetJournal(path)
        seq = j.begin("spawn", replica=3, role="mixed")
        # the begin is ON DISK before any process action: a reader
        # sees the intent even if the writer dies right here
        body, err = load_journal(path)
        assert err is None
        opens = open_actions(body)
        assert [a["seq"] for a in opens] == [seq]
        assert opens[0]["action"] == "spawn"
        j.update(seq, phase="launched", pid=4242, port=9999)
        body, _ = load_journal(path)
        # launched overlays its fields onto the merged open action
        assert open_actions(body)[0]["pid"] == 4242
        j.commit(seq)
        body, _ = load_journal(path)
        assert open_actions(body) == []
        assert j.seq == seq

    def test_rollback_resolves_and_crc_rejects_tamper(self, tmp_path):
        path = str(tmp_path / "j.json")
        j = FleetJournal(path)
        seq = j.begin("drain", replica=0)
        j.rollback(seq, reason="readmitted_below_min")
        body, err = load_journal(path)
        assert err is None and open_actions(body) == []
        # tamper one byte of the body: crc must refuse the whole file
        obj = json.loads(open(path).read())
        obj["body"]["seq"] = 999
        open(path, "w").write(json.dumps(obj))
        body, err = load_journal(path)
        assert body is None and "crc mismatch" in err

    def test_torn_write_leaves_previous_state(self, tmp_path):
        path = str(tmp_path / "j.json")
        j = FleetJournal(path)
        j.begin("spawn", replica=0)
        before = open(path).read()
        # a crash mid-write abandons the tmp; the rename is the commit
        open(path + ".tmp", "w").write(before[: len(before) // 2])
        body, err = load_journal(path)
        assert err is None and body is not None
        assert open(path).read() == before

    def test_bounded_tail_never_drops_unresolved(self, tmp_path):
        j = FleetJournal(str(tmp_path / "j.json"))
        stuck = j.begin("drain", replica=0)  # never resolved
        for _ in range(FleetJournal.MAX_ACTION_ENTRIES):
            s = j.begin("spawn", replica=1)
            j.commit(s)
        body, _ = load_journal(j.path)
        assert [a["seq"] for a in open_actions(body)] == [stuck]

    def test_adopt_body_keeps_seq_monotonic_across_generations(
            self, tmp_path):
        path = str(tmp_path / "j.json")
        j1 = FleetJournal(path)
        s1 = j1.begin("spawn", replica=0)
        j1.commit(s1)
        body, _ = load_journal(path)
        j2 = FleetJournal(path)  # the restarted supervisor
        j2.adopt_body(body)
        s2 = j2.begin("spawn", replica=1)
        assert s2 > s1
        body, _ = load_journal(path)
        assert body["supervisor_pid"] == os.getpid()

    def test_flight_inspect_lints_journal_bytes(self, tmp_path):
        fin = _load_tool("flight_inspect")
        path = str(tmp_path / "j.json")
        j = FleetJournal(path)
        seq = j.begin("spawn", replica=1, role="mixed")
        j.update(seq, phase="launched", pid=1234, port=8901)
        j.commit(seq)
        j.record_fleet([{"idx": 0, "pid": 111, "port": 8800,
                         "role": "mixed"},
                        {"idx": 1, "pid": 1234, "port": 8901,
                         "role": "mixed"}])
        obj = json.loads(open(path).read())
        assert fin.lint_fleet_journal(obj, allow_open_tail=0) == []
        # an open begin fails the strict lint and passes the tolerant
        # one — the chaos harness's "everything resolved" assertion
        j.begin("drain", replica=0)
        obj = json.loads(open(path).read())
        assert fin.lint_fleet_journal(obj, allow_open_tail=0)
        assert fin.lint_fleet_journal(obj, allow_open_tail=1) == []

    def test_write_failure_counted_not_raised(self, tmp_path):
        # journal "directory" is a regular file: every write fails —
        # counted, never raised; the fleet must keep running (chmod
        # tricks don't work for root, a file-as-parent does)
        blocker = tmp_path / "blocker"
        blocker.write_text("")
        j = FleetJournal(str(blocker / "j.json"))
        j.begin("spawn", replica=0)  # must not raise
        assert j.write_failures_total >= 1
        assert j.writes_total == 0


# ---------------------------------------------------------------------------
# plan_recovery: the pure restart contract
# ---------------------------------------------------------------------------

def _body(fleet=(), actions=(), seq=None):
    seqs = [a["seq"] for a in actions] or [0]
    return {"seq": seq if seq is not None else max(seqs),
            "supervisor_pid": 12345,
            "fleet": list(fleet), "actions": list(actions)}


class TestPlanRecovery:
    def test_adopts_live_respawns_dead(self):
        body = _body(fleet=[
            {"idx": 0, "pid": 100, "port": 8800, "role": "mixed"},
            {"idx": 1, "pid": 101, "port": 8801, "role": "decode"}])
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: pid == 100)
        assert [e["idx"] for e in plan["adopt"]] == [0]
        assert plan["respawn"] == [{"idx": 1, "role": "decode"}]
        assert plan["reap"] == [] and plan["resume"] == []

    def test_scan_overlays_stale_snapshot_pid(self):
        # monitor respawned replica 0 after the last snapshot: journal
        # pid is dead, the env-marker scan has the live one — adopt the
        # scanned pid, never respawn a duplicate
        body = _body(fleet=[{"idx": 0, "pid": 100, "port": 8800,
                             "role": "mixed"}])
        scan = {0: {"pid": 200, "port": 8810}}
        plan = plan_recovery(body, scan, 1, 4,
                             alive=lambda pid, port: pid == 200)
        assert [(e["idx"], e["pid"]) for e in plan["adopt"]] == \
            [(0, 200)]
        assert plan["respawn"] == []

    def test_open_spawn_live_under_envelope_adopted_and_committed(self):
        act = [{"seq": 5, "action": "spawn", "phase": "begin",
                "replica": 1, "role": "mixed"},
               {"seq": 5, "phase": "launched", "pid": 300,
                "port": 8900}]
        body = _body(fleet=[{"idx": 0, "pid": 100, "port": 8800,
                             "role": "mixed"}], actions=act)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        assert sorted(e["idx"] for e in plan["adopt"]) == [0, 1]
        assert plan["resolve"] == [(5, "commit", "adopted_on_recovery")]

    def test_open_spawn_live_over_envelope_reaped(self):
        act = [{"seq": 5, "action": "spawn", "phase": "begin",
                "replica": 1, "role": "mixed"},
               {"seq": 5, "phase": "launched", "pid": 300,
                "port": 8900}]
        body = _body(fleet=[{"idx": 0, "pid": 100, "port": 8800,
                             "role": "mixed"}], actions=act)
        plan = plan_recovery(body, {}, 1, 1,  # max=1: no room
                             alive=lambda pid, port: True)
        assert [e["pid"] for e in plan["reap"]] == [300]
        assert plan["resolve"] == \
            [(5, "rollback", "reaped_over_envelope")]

    def test_open_spawn_dead_rolled_back_nothing_to_reap(self):
        act = [{"seq": 5, "action": "spawn", "phase": "begin",
                "replica": 1, "role": "mixed"}]
        body = _body(fleet=[{"idx": 0, "pid": 100, "port": 8800,
                             "role": "mixed"}], actions=act)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: pid == 100)
        assert plan["reap"] == []
        assert plan["resolve"] == [(5, "rollback", "orphan_dead")]

    def test_open_drain_victim_dead_committed(self):
        act = [{"seq": 7, "action": "drain", "phase": "begin",
                "replica": 1, "pid": 101, "port": 8801}]
        body = _body(fleet=[
            {"idx": 0, "pid": 100, "port": 8800, "role": "mixed"},
            {"idx": 1, "pid": 101, "port": 8801, "role": "mixed"}],
            actions=act)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: pid == 100)
        assert plan["resolve"] == \
            [(7, "commit", "victim_already_dead")]
        assert [e["idx"] for e in plan["adopt"]] == [0]

    def test_open_drain_victim_live_resumed_with_draining_flag(self):
        act = [{"seq": 7, "action": "drain", "phase": "begin",
                "replica": 1, "pid": 101, "port": 8801}]
        body = _body(fleet=[
            {"idx": 0, "pid": 100, "port": 8800, "role": "mixed"},
            {"idx": 1, "pid": 101, "port": 8801, "role": "mixed"}],
            actions=act)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        assert plan["resume"] == [{"seq": 7, "action": "drain",
                                   "replica": 1}]
        dr = [e for e in plan["adopt"] if e["idx"] == 1]
        assert dr and dr[0].get("draining") is True

    def test_open_drain_readmitted_when_below_min(self):
        # killing the victim now would empty the fleet: roll back and
        # re-admit it as a full member instead
        act = [{"seq": 7, "action": "drain", "phase": "begin",
                "replica": 0, "pid": 100, "port": 8800}]
        body = _body(fleet=[{"idx": 0, "pid": 100, "port": 8800,
                             "role": "mixed"}], actions=act)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        assert plan["resume"] == []
        assert plan["resolve"] == \
            [(7, "rollback", "readmitted_below_min")]
        ent = [e for e in plan["adopt"] if e["idx"] == 0][0]
        assert not ent.get("draining")

    def test_open_rerole_live_resumes_dead_respawns_with_new_role(self):
        act = [{"seq": 9, "action": "rerole", "phase": "begin",
                "replica": 1, "pid": 101, "port": 8801,
                "role_from": "mixed", "role_to": "prefill"}]
        body = _body(fleet=[
            {"idx": 0, "pid": 100, "port": 8800, "role": "mixed"},
            {"idx": 1, "pid": 101, "port": 8801, "role": "mixed"}],
            actions=act)
        live = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        assert live["resume"] == [{"seq": 9, "action": "rerole",
                                   "replica": 1, "role": "prefill"}]
        dead = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: pid == 100)
        assert {"idx": 1, "role": "prefill"} in dead["respawn"]
        assert dead["resolve"] == \
            [(9, "commit", "respawned_with_new_role")]

    def test_never_double_spawn_idx_claimed_once(self):
        # the same replica appears in the fleet snapshot AND the scan
        # AND an open spawn: exactly one adoption, zero respawns
        act = [{"seq": 5, "action": "spawn", "phase": "begin",
                "replica": 1, "role": "mixed"},
               {"seq": 5, "phase": "launched", "pid": 300,
                "port": 8900}]
        body = _body(fleet=[
            {"idx": 0, "pid": 100, "port": 8800, "role": "mixed"},
            {"idx": 1, "pid": 300, "port": 8900, "role": "mixed"}],
            actions=act)
        scan = {1: {"pid": 300, "port": 8900}}
        plan = plan_recovery(body, scan, 1, 4,
                             alive=lambda pid, port: True)
        assert sorted(e["idx"] for e in plan["adopt"]) == [0, 1]
        assert plan["respawn"] == []


# ---------------------------------------------------------------------------
# Scale-down guard: typed refusals (satellite 1)
# ---------------------------------------------------------------------------

class TestScaleDownGuard:
    def test_last_replica_refused(self, tmp_path):
        sup = _sup(1, tmp=tmp_path)
        assert sup.scale_down_guard(0) == "last_replica"
        out = sup.drain_replica(0)
        assert out["refused"] == "last_replica"
        assert out["drained"] is False

    def test_below_min_envelope_refused(self, tmp_path):
        sup = _sup(2, tmp=tmp_path)
        assert sup.scale_down_guard(0, min_replicas=2) == \
            "below_min_replicas(2)"
        assert sup.scale_down_guard(0, min_replicas=1) is None

    def test_last_role_advertising_replica_refused(self, tmp_path):
        sup = _sup(3, roles=["prefill", "decode", "decode"],
                   tmp=tmp_path)
        assert sup.scale_down_guard(0) == "last_prefill_replica"
        assert sup.scale_down_guard(1) is None  # a decode survives
        sup.replicas[2].draining = True  # draining is not a survivor
        assert sup.scale_down_guard(1) == "last_decode_replica"

    def test_unknown_idx_typed(self, tmp_path):
        sup = _sup(1, tmp=tmp_path)
        assert sup.scale_down_guard(99) == "no_such_replica"

    def test_mid_drain_victim_skips_guard(self, tmp_path):
        # recovery re-drains a victim whose removal was already
        # committed to — the guard must not refuse it
        sup = _sup(1, tmp=tmp_path)
        sup.replicas[0].draining = True
        out = sup.drain_replica(0)
        assert "refused" not in out


# ---------------------------------------------------------------------------
# Backoff reset on healthy probe (satellite 2)
# ---------------------------------------------------------------------------

class TestBackoffReset:
    def test_reset_backoff_clears_the_exponential_state(self):
        rep = Replica(0, "127.0.0.1")
        rep.consec_deaths = 5
        rep.probe_failures = 2
        rep.next_spawn_t = time.monotonic() + 60.0
        rep.reset_backoff()
        assert rep.consec_deaths == 0
        assert rep.probe_failures == 0
        assert rep.next_spawn_t is None


# ---------------------------------------------------------------------------
# Rendezvous churn: minimal key reassignment (satellite 3, unit half)
# ---------------------------------------------------------------------------

class _Cand:
    def __init__(self, idx):
        self.idx = idx


class TestRendezvousChurn:
    KEYS = [f"{i:016x}" for i in range(256)]

    def _owners(self, cands):
        return {k: rendezvous_owner(k, cands).idx for k in self.KEYS}

    def test_scale_up_moves_only_the_new_replicas_keys(self):
        old = [_Cand(i) for i in range(3)]
        new = old + [_Cand(3)]
        before, after = self._owners(old), self._owners(new)
        moved = [k for k in self.KEYS if before[k] != after[k]]
        assert moved, "a new replica must win some keys"
        assert all(after[k] == 3 for k in moved)
        # and roughly its fair share, not the whole keyspace
        assert len(moved) < len(self.KEYS) // 2

    def test_scale_down_moves_only_the_victims_keys(self):
        old = [_Cand(i) for i in range(4)]
        new = [c for c in old if c.idx != 2]
        before, after = self._owners(old), self._owners(new)
        for k in self.KEYS:
            if before[k] != 2:
                assert after[k] == before[k], \
                    "a survivor's keys must not move on scale-down"
            else:
                assert after[k] != 2


# ---------------------------------------------------------------------------
# Shape rule: desired_prefill + plan_shape (the README rule, executable)
# ---------------------------------------------------------------------------

class TestShapeRule:
    def test_desired_prefill_ratio_and_clamps(self):
        assert desired_prefill(0) == 0
        assert desired_prefill(1) == 0  # no shape below 2 replicas
        assert desired_prefill(2) == 1
        assert desired_prefill(4) == 1            # 1 prefill : 3 decode
        assert desired_prefill(8) == 2
        assert desired_prefill(4, decode_per_prefill=1.0) == 2
        # bias never strands a class: clamped to [1, n-1]
        assert desired_prefill(2, bias=-5) == 1
        assert desired_prefill(2, bias=+5) == 1
        assert desired_prefill(4, bias=+1) == 2
        assert desired_prefill(4, bias=-1) == 1

    def _asc(self, sup, tmp):
        return Autoscaler(sup, AutoscaleConfig(
            min_replicas=1, max_replicas=8),
            journal_path=str(tmp / "j.json"))

    def test_mixed_only_fleet_never_shaped(self, tmp_path):
        asc = self._asc(_sup(3, tmp=tmp_path), tmp_path)
        assert asc.plan_shape() is None

    def test_underrepresented_prefill_converts_a_mixed(self, tmp_path):
        sup = _sup(4, roles=["decode", "decode", "decode", "mixed"],
                   tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        plan = asc.plan_shape()
        assert plan == {"replica": 3, "role": "prefill",
                        "reason": "shape_prefill_up"}

    def test_overrepresented_prefill_converts_to_decode(self, tmp_path):
        sup = _sup(4, roles=["prefill", "prefill", "decode", "decode"],
                   tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        plan = asc.plan_shape()
        assert plan == {"replica": 0, "role": "decode",
                        "reason": "shape_decode_up"}

    def test_balanced_fleet_not_shaped(self, tmp_path):
        sup = _sup(2, roles=["prefill", "decode"], tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        assert asc.plan_shape() is None  # already at desired shape

    def test_handoff_failure_climb_biases_prefill_up(self, tmp_path):
        sup = _sup(4, roles=["prefill", "decode", "decode", "decode"],
                   tmp=tmp_path)

        class _R:
            handoff_prefill_failures_total = 3
        sup.router = _R()
        asc = self._asc(sup, tmp_path)
        # want jumps from 1 to 2: a decode donates (no mixed left)
        plan = asc.plan_shape()
        assert plan is not None and plan["role"] == "prefill"
        # the climb is edge-triggered: same counter, no second bump
        assert asc.plan_shape() is None


# ---------------------------------------------------------------------------
# Actuator refusals + observability (no processes)
# ---------------------------------------------------------------------------

class TestActuatorRefusals:
    def _asc(self, sup, tmp, **cfg):
        kw = dict(min_replicas=1, max_replicas=2)
        kw.update(cfg)
        return Autoscaler(sup, AutoscaleConfig(**kw),
                          journal_path=str(tmp / "j.json"))

    def test_envelope_validated(self, tmp_path):
        sup = _sup(1, tmp=tmp_path)
        with pytest.raises(ValueError):
            Autoscaler(sup, AutoscaleConfig(min_replicas=0),
                       journal_path=str(tmp_path / "j.json"))
        with pytest.raises(ValueError):
            Autoscaler(sup, AutoscaleConfig(min_replicas=3,
                                            max_replicas=2),
                       journal_path=str(tmp_path / "j2.json"))

    def test_scale_up_refused_at_max_even_forced(self, tmp_path):
        sup = _sup(2, tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        out = asc.scale_up(force=True)
        assert out["ok"] is False and out["reason"] == "refused_at_max"
        assert asc.actions_total[("spawn", "refused_at_max")] == 1

    def test_scale_up_refused_in_cooldown(self, tmp_path):
        sup = _sup(1, tmp=tmp_path)
        asc = self._asc(sup, tmp_path, max_replicas=4,
                        cooldown_up_s=3600.0)
        asc._last_up_t = time.monotonic()
        out = asc.scale_up()
        assert out["reason"] == "refused_cooldown"
        st = asc.status()
        assert st["cooldown_up_remaining_s"] > 0

    def test_scale_down_refused_no_eligible_victim(self, tmp_path):
        sup = _sup(1, tmp=tmp_path)  # the guard protects the only one
        asc = self._asc(sup, tmp_path)
        out = asc.scale_down(force=True)
        assert out["reason"] == "refused_no_eligible_victim"

    def test_rerole_typed_refusals(self, tmp_path):
        sup = _sup(2, roles=["prefill", "decode"], tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        assert asc.rerole(0, "gpu", force=True)["reason"] == \
            "refused_bad_role_gpu"
        assert asc.rerole(9, "decode", force=True)["reason"] == \
            "refused_no_such_replica"
        assert asc.rerole(0, "prefill", force=True)["reason"] == \
            "refused_already_that_role"
        # converting the last prefill would strand the class
        assert asc.rerole(0, "decode", force=True)["reason"] == \
            "refused_guard"

    def test_refusals_never_touch_the_journal(self, tmp_path):
        sup = _sup(2, tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        seq0 = asc.journal.seq
        asc.scale_up(force=True)           # at_max
        asc.rerole(0, "bogus", force=True)
        assert asc.journal.seq == seq0

    def test_prometheus_families_and_status(self, tmp_path):
        sup = _sup(2, roles=["prefill", "decode"], tmp=tmp_path)
        asc = self._asc(sup, tmp_path)
        asc.scale_up(force=True)  # refused: still a counted action
        lines = asc.prometheus_lines()
        text = "\n".join(lines)
        assert "# TYPE serving_autoscale_actions_total counter" in text
        assert 'serving_autoscale_actions_total{action="spawn",' \
               'reason="refused_at_max"} 1' in text
        assert 'serving_fleet_replicas{role="prefill"} 1' in text
        assert 'serving_fleet_replicas{role="decode"} 1' in text
        assert 'serving_fleet_replicas{role="mixed"} 0' in text
        st = asc.status()
        assert st["replicas_by_role"] == {"prefill": 1, "decode": 1}
        assert st["last_action"]["reason"] == "refused_at_max"
        assert st["actions_total"] == {"spawn|refused_at_max": 1}
        assert st["action_in_flight"] is False
        assert st["journal"]["path"] == str(tmp_path / "j.json")


# ---------------------------------------------------------------------------
# Flight-recorder autoscale bundles lint (satellite 4+6)
# ---------------------------------------------------------------------------

class TestAutoscaleBundleLint:
    def _bundle(self, **over):
        b = {"v": 1, "reason": "autoscale", "t_unix": time.time(),
             "pid": os.getpid(),
             "action": {"action": "spawn", "reason": "pressure",
                        "ok": True, "t_unix": time.time()},
             "fleet": [{"idx": 0, "pid": 1, "port": 8800,
                        "role": "mixed"}],
             "journal_tail": [{"seq": 1, "phase": "begin",
                               "action": "spawn"},
                              {"seq": 1, "phase": "commit"}]}
        b.update(over)
        return b

    def test_wellformed_bundle_lints_clean(self):
        fin = _load_tool("flight_inspect")
        assert fin.lint_bundle(self._bundle()) == []

    def test_malformed_bundles_rejected(self):
        fin = _load_tool("flight_inspect")
        assert fin.lint_bundle(self._bundle(action="not-a-dict"))
        assert fin.lint_bundle(self._bundle(
            journal_tail=[{"seq": 1, "phase": "exploded"}]))
        bad = self._bundle()
        del bad["fleet"]
        assert fin.lint_bundle(bad)

    def test_recorder_written_bundle_lints_end_to_end(self, tmp_path):
        # the actual write path: a refused action via an Autoscaler
        # wired to a real FlightRecorder produces a lint-clean bundle
        from paddle_tpu.serving.fleet_metrics import FlightRecorder
        fin = _load_tool("flight_inspect")
        sup = _sup(2, tmp=tmp_path)
        flight = FlightRecorder(str(tmp_path / "flight"),
                                min_interval_s=0.0)
        asc = Autoscaler(sup, AutoscaleConfig(min_replicas=1,
                                              max_replicas=2),
                         journal_path=str(tmp_path / "j.json"),
                         flight=flight)
        out = asc.scale_up(force=True)  # refused_at_max -> no bundle
        assert out["ok"] is False
        asc._record("drain", "unit", ok=True, replica=1)  # bundled
        bundles, errors = fin.lint_dir(str(tmp_path / "flight"))
        assert errors == []
        assert len(bundles) == 1


# ---------------------------------------------------------------------------
# Conftest stray-guard: adopted replicas are spared (satellite 6)
# ---------------------------------------------------------------------------

class TestConftestAdoption:
    def _conftest(self):
        spec = importlib.util.spec_from_file_location(
            "_conftest_under_test",
            REPO / "tests" / "conftest.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _marked_child(self, journal):
        env = dict(os.environ)
        env["PT_SUPERVISOR_JOURNAL"] = journal
        env["PT_REPLICA_IDX"] = "0"
        return subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(60)"],
                                env=env)

    def test_live_supervisor_in_journal_spares_the_orphan(
            self, tmp_path):
        ct = self._conftest()
        j = FleetJournal(str(tmp_path / "j.json"))  # our pid, alive
        j.record_fleet([])
        child = self._marked_child(j.path)
        try:
            # /proc/<pid>/environ shows the PRE-exec image for a
            # moment after Popen returns — wait for the marker
            deadline = time.monotonic() + 10
            while not ct._adopted_by_live_supervisor(child.pid) \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ct._adopted_by_live_supervisor(child.pid) is True
        finally:
            child.kill()
            child.wait()

    def test_dead_supervisor_or_no_marker_is_killable(self, tmp_path):
        ct = self._conftest()
        path = str(tmp_path / "j.json")
        dead = 2 ** 22 + 7919  # beyond default pid_max: never alive
        obj = {"v": 1, "body": {"supervisor_pid": dead}}
        open(path, "w").write(json.dumps(obj))
        child = self._marked_child(path)
        unmarked = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"])
        try:
            assert ct._adopted_by_live_supervisor(child.pid) is False
            assert ct._adopted_by_live_supervisor(unmarked.pid) \
                is False
        finally:
            for p in (child, unmarked):
                p.kill()
                p.wait()


# ---------------------------------------------------------------------------
# Journal env markers on spawned replicas
# ---------------------------------------------------------------------------

class TestJournalEnvMarkers:
    def test_scan_finds_marked_server_lookalike(self, tmp_path):
        # a process whose cmdline matches the server module AND whose
        # env carries our journal marker is found by the scan; the
        # same command without the marker is not
        journal = str(tmp_path / "j.json")
        env = dict(os.environ)
        env["PT_SUPERVISOR_JOURNAL"] = journal
        env["PT_REPLICA_IDX"] = "3"
        code = ("import sys, time; "
                "sys.argv=['paddle_tpu.serving.server']; "
                "time.sleep(60)")
        marked = subprocess.Popen(
            [sys.executable, "-c", code, "paddle_tpu.serving.server",
             "--port", "8899"], env=env)
        try:
            deadline = time.monotonic() + 10
            found = {}
            while time.monotonic() < deadline:
                found = scan_marked_replicas(journal)
                if found:
                    break
                time.sleep(0.1)
            assert found == {3: {"pid": marked.pid, "port": 8899}}
            assert scan_marked_replicas(
                str(tmp_path / "other.json")) == {}
        finally:
            marked.kill()
            marked.wait()


# ---------------------------------------------------------------------------
# Integration (slow lane): live fleet, bit-identical across scale
# events; chaos INVARIANT 7
# ---------------------------------------------------------------------------

def _replica_env(cache_dir):
    env = {"JAX_PLATFORMS": "cpu", "TPU_SKIP_MDS_QUERY": "true",
           "PADDLE_TPU_COMPILE_CACHE": cache_dir}
    return env


@pytest.mark.slow
class TestAutoscalerLive:
    def test_bit_identical_keyed_tokens_across_scale_events(
            self, tmp_path):
        """Satellite 3 (integration half): keyed greedy outputs from
        a live autoscaled fleet are bit-identical before a scale-up,
        after it, and after the scale-down that follows — chains
        either stay where the rendezvous put them or are handed to a
        survivor, never corrupted."""
        import numpy as np

        from paddle_tpu.serving.server import client_request
        from paddle_tpu.serving.supervisor import FailoverRouter

        chaos = _load_tool("chaos_serving")
        rng = np.random.default_rng(0)
        prompts = [np.asarray(rng.integers(1, 100, size=20), np.int32)
                   for _ in range(4)]
        expected = chaos._reference_outputs("gpt_tiny", prompts,
                                            [5] * 4, 8, 96)
        cache = str(tmp_path / "cache")
        sup = Supervisor(
            model="gpt_tiny", replicas=1,
            server_args=["--page-size", "8", "--max-seq-len", "96",
                         "--num-slots", "2"],
            replica_env=_replica_env(cache),
            probe_interval_s=0.3, backoff_base_s=0.5,
            log_dir=str(tmp_path / "logs"))
        asc = Autoscaler(sup, AutoscaleConfig(
            min_replicas=1, max_replicas=2, cooldown_up_s=0.0,
            cooldown_down_s=0.0),
            journal_path=str(tmp_path / "j.json"))
        router = None
        try:
            sup.start(wait_ready=True)
            router = FailoverRouter(sup, port=0)
            port = router.start()

            def run_all():
                outs = []
                for i, p in enumerate(prompts):
                    r = client_request(
                        "127.0.0.1", port,
                        {"op": "generate",
                         "prompt": [int(t) for t in p],
                         "max_new_tokens": 5,
                         "key": f"asl-{i}"}, timeout_s=180.0)
                    assert not r.get("error"), r
                    outs.append(r["generated"])
                return outs

            assert run_all() == expected
            up = asc.scale_up(reason="test", force=True)
            assert up["ok"] is True, up
            assert len(sup.replicas) == 2
            assert run_all() == expected
            down = asc.scale_down(reason="test", force=True)
            assert down["ok"] is True, down
            assert len(sup.replicas) == 1
            # survivors serve every key: handed-off chains or
            # re-prefill-on-first-use, identical tokens either way
            assert run_all() == expected
            # journal reflects the full story and lints strictly
            fin = _load_tool("flight_inspect")
            obj = json.loads(open(asc.journal.path).read())
            assert fin.lint_fleet_journal(obj,
                                          allow_open_tail=0) == []
            kinds = [a["action"] for a in asc.journal.tail(99)
                     if a.get("phase") == "begin"]
            assert kinds == ["spawn", "drain"]
        finally:
            if router is not None:
                router.stop()
            sup.stop()

    def test_chaos_invariant7_supervisor_sigkill_recovery(self):
        """ISSUE r21 acceptance: the full invariant-7 chaos run —
        SIGKILL the supervisor mid-spawn and mid-scale-down under
        keyed traffic, restart from the journal, assert no stranded
        processes, no lost chains, zero leaked pages, 100% typed
        termination, journal + flight bundles lint clean."""
        chaos = _load_tool("chaos_serving")
        report = chaos.run_autoscale_chaos(requests=6, seed=0)
        assert report.ok, report.to_dict()
        assert report.recoveries == 2
        assert report.stranded_processes == 0
        assert report.journal_lint_failures == 0
        assert report.mismatches == 0
        assert report.hangs == 0
        assert report.completed + report.typed_errors == 6
