"""InMemory/Queue datasets (fluid/dataset.py parity) and PS geo-SGD /
SSD sparse tables (sparse_geo_table.cc, ssd_sparse_table.cc parity)."""

import numpy as np
import pytest

from paddle_tpu.io.heavy_dataset import (InMemoryDataset, QueueDataset,
                                         parse_slot_line)


def _write_slot_files(tmp_path, n_files=3, rows_per=20):
    files = []
    idx = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i:03d}.txt"
        with open(p, "w") as f:
            for _ in range(rows_per):
                f.write(f"id:{idx};feat:{idx * 0.5} {idx + 1.5};"
                        f"label:{idx % 2}\n")
                idx += 1
        files.append(str(p))
    return files, idx


def test_parse_slot_line():
    s = parse_slot_line("id:7 8;feat:0.5 1.5;label:1")
    np.testing.assert_array_equal(s["id"], [7, 8])
    assert s["id"].dtype == np.int64
    np.testing.assert_allclose(s["feat"], [0.5, 1.5])
    assert s["feat"].dtype == np.float32


def test_in_memory_dataset_load_and_batch(tmp_path):
    files, total = _write_slot_files(tmp_path)
    ds = InMemoryDataset()
    ds.set_filelist([str(tmp_path / "part-*.txt")])
    assert len(ds.filelist) == 3
    ds.set_thread(2)
    ds.set_batch_size(8)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == total
    batches = list(ds)
    assert sum(len(b) for b in batches) == total
    assert len(batches[0]) == 8
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_in_memory_local_shuffle_deterministic(tmp_path):
    files, total = _write_slot_files(tmp_path)
    ids = []
    for _ in range(2):
        ds = InMemoryDataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.set_shuffle_seed(42)
        ds.local_shuffle()
        ids.append([int(s["id"][0]) for s in ds.samples])
    assert ids[0] == ids[1]
    assert ids[0] != sorted(ids[0])  # actually shuffled


def test_in_memory_global_shuffle_partitions(tmp_path):
    files, total = _write_slot_files(tmp_path)
    seen = []
    for rank in range(4):
        ds = InMemoryDataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.global_shuffle(rank=rank, world_size=4)
        seen.append({int(s["id"][0]) for s in ds.samples})
    union = set().union(*seen)
    assert union == set(range(total))  # disjoint cover
    for a in range(4):
        for b in range(a + 1, 4):
            assert not (seen[a] & seen[b])


def test_queue_dataset_streams_all(tmp_path):
    files, total = _write_slot_files(tmp_path)
    ds = QueueDataset(capacity=16)
    ds.set_filelist(files)
    ds.set_thread(2)
    ds.set_batch_size(7)
    got = [s for b in ds for s in b]
    assert len(got) == total
    assert {int(s["id"][0]) for s in got} == set(range(total))
    # second epoch works (fresh readers)
    assert sum(len(b) for b in ds) == total


def test_channels_split(tmp_path):
    files, total = _write_slot_files(tmp_path)
    ds = InMemoryDataset()
    ds.set_filelist(files)
    ds.load_into_memory()
    chans = ds.channels(4)
    assert sum(len(c) for c in chans) == total


def test_in_memory_parse_error_propagates(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("id:1;feat:0.5\nid:not_an_int;feat:0.5\n")
    ds = InMemoryDataset()
    ds.set_filelist([str(p)])
    with pytest.raises(ValueError):
        ds.load_into_memory()


def test_queue_parse_error_propagates(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("id:1\nid:oops\n")
    ds = QueueDataset()
    ds.set_filelist([str(p)])
    with pytest.raises(ValueError):
        list(ds)


def test_queue_early_stop_releases_readers(tmp_path):
    import gc
    import threading
    import time
    files, total = _write_slot_files(tmp_path, n_files=2, rows_per=200)
    before = threading.active_count()
    for _ in range(3):  # repeated abandoned epochs must not leak threads
        ds = QueueDataset(capacity=4)
        ds.set_filelist(files)
        ds.set_thread(2)
        ds.set_batch_size(2)
        it = iter(ds)
        next(it)  # consume one batch, abandon the rest
        del it
    gc.collect()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 1, "reader threads leaked"


def test_sample_key_spreads_low_cardinality(tmp_path):
    # binary first-slot values must still shard across all ranks
    from paddle_tpu.io.heavy_dataset import _sample_key
    keys = {_sample_key({"click": np.asarray([i % 2], np.int64),
                         "id": np.asarray([i], np.int64)}) % 4
            for i in range(100)}
    assert keys == {0, 1, 2, 3}  # whole-sample hash: all shards covered


# ------------------------------------------------------------- PS tables

def test_ssd_sparse_table_matches_mem_table(tmp_path, rng):
    from paddle_tpu.distributed.ps import SparseTable, SSDSparseTable
    mem = SparseTable(emb_dim=4, lr=0.1)
    ssd = SSDSparseTable(emb_dim=4, lr=0.1,
                         path=str(tmp_path / "rows.db"), cache_rows=2)
    keys = np.array([1, 5, 9, 1], np.int64)
    base_m = mem.pull(keys)
    base_s = ssd.pull(keys)
    np.testing.assert_allclose(base_m, base_s)  # same seeded init
    for _ in range(3):
        g = rng.normal(size=(4, 4)).astype(np.float32)
        mem.push_grad(keys, g)
        ssd.push_grad(keys, g)
    np.testing.assert_allclose(mem.pull(keys), ssd.pull(keys), rtol=1e-5)
    assert ssd.size() == mem.size() == 3


def test_ssd_table_persists_across_reopen(tmp_path):
    from paddle_tpu.distributed.ps import SSDSparseTable
    path = str(tmp_path / "p.db")
    t1 = SSDSparseTable(emb_dim=3, path=path)
    rows = t1.pull(np.array([10, 20], np.int64))
    t1.flush()
    t2 = SSDSparseTable(emb_dim=3, path=path)
    np.testing.assert_allclose(t2.pull(np.array([10, 20], np.int64)),
                               rows)


def test_geo_sgd_end_to_end():
    from paddle_tpu.distributed.ps import (GeoCommunicator, PSClient,
                                           PSServer)
    srv = PSServer()
    srv.add_sparse_table("emb", emb_dim=4, initializer_std=0.0)
    srv.start()
    try:
        c1 = PSClient([srv.endpoint])
        c2 = PSClient([srv.endpoint])
        geo1 = GeoCommunicator(c1, "emb", 4, k_steps=2, lr=0.5)
        geo2 = GeoCommunicator(c2, "emb", 4, k_steps=2, lr=0.5)
        keys = np.array([3], np.int64)
        g = np.ones((1, 4), np.float32)
        # both trainers do 2 local steps -> each syncs delta -1.0*lr*2
        for _ in range(2):
            geo1.pull(keys)
            geo1.push_grad(keys, g)
        for _ in range(2):
            geo2.pull(keys)
            geo2.push_grad(keys, g)
        # server merged both deltas: 2 trainers * 2 steps * 0.5 = 2.0
        srv_val = c1.pull_sparse("emb", keys)
        np.testing.assert_allclose(srv_val, -2.0, rtol=1e-6)
        # trainer 2's replica refreshed to include trainer 1's work
        np.testing.assert_allclose(geo2.local[3], -2.0, rtol=1e-6)
        c1.stop()
    finally:
        srv.stop()


def test_ssd_rows_survive_server_stop(tmp_path):
    # dirty cached rows must be committed when the server stops
    from paddle_tpu.distributed.ps import PSClient, PSServer, \
        SSDSparseTable
    path = str(tmp_path / "persist.db")
    srv = PSServer()
    srv.add_sparse_table("emb", emb_dim=2, kind="ssd", path=path,
                         initializer_std=0.0)
    srv.start()
    c = PSClient([srv.endpoint])
    c.push_sparse_grad("emb", np.array([7], np.int64),
                       np.ones((1, 2), np.float32))
    want = c.pull_sparse("emb", np.array([7], np.int64))
    c.stop()
    srv.stop()
    reopened = SSDSparseTable(emb_dim=2, path=path)
    np.testing.assert_allclose(
        reopened.pull(np.array([7], np.int64)), want)


def test_geo_replica_eviction():
    from paddle_tpu.distributed.ps import (GeoCommunicator, PSClient,
                                           PSServer)
    srv = PSServer()
    srv.add_sparse_table("emb", emb_dim=2, initializer_std=0.0)
    srv.start()
    try:
        c = PSClient([srv.endpoint])
        geo = GeoCommunicator(c, "emb", 2, k_steps=1, max_local_rows=3)
        for k in range(10):
            keys = np.array([k], np.int64)
            geo.push_grad(keys, np.ones((1, 2), np.float32))
        assert len(geo.local) <= 3 and len(geo.base) <= 3
        # pull-only traffic is bounded too (read-heavy eval loops)
        for k in range(20, 40):
            geo.pull(np.array([k], np.int64))
        assert len(geo.local) <= 4  # cap + the protected current key
        # evicted rows re-pull the server view transparently
        out = geo.pull(np.array([0], np.int64))
        np.testing.assert_allclose(out, -0.01, rtol=1e-5)
        c.stop()
    finally:
        srv.stop()


def test_server_hosts_ssd_table():
    from paddle_tpu.distributed.ps import PSClient, PSServer
    srv = PSServer()
    srv.add_sparse_table("big", emb_dim=2, kind="ssd",
                         initializer_std=0.0)
    srv.start()
    try:
        c = PSClient([srv.endpoint])
        keys = np.array([100, 200], np.int64)
        c.push_sparse_grad("big", keys, np.ones((2, 2), np.float32))
        out = c.pull_sparse("big", keys)
        assert out.shape == (2, 2) and (out != 0).all()
        c.stop()
    finally:
        srv.stop()
