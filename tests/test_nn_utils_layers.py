"""weight_norm / spectral_norm hooks + new layer wrappers.

Reference parity: python/paddle/fluid/tests/unittests/test_weight_norm_hook
.py, test_spectral_norm_op.py, and the layer-API tests for Pad3D/Fold/
LPPool2D/loss layers."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn


def test_weight_norm_reparam_and_remove():
    pt.seed(0)
    lin = nn.Linear(6, 4)
    w0 = lin.weight.numpy().copy()
    nn.utils.weight_norm(lin, "weight", dim=0)
    names = [n for n, _ in lin.named_parameters()]
    assert "weight_g" in names and "weight_v" in names
    assert "weight" not in names
    x = pt.to_tensor(np.random.default_rng(0).standard_normal(
        (3, 6)).astype("f"))
    y = lin(x)
    # reparametrized forward == original weight forward at init
    np.testing.assert_allclose(np.asarray(y.value), x.numpy() @ w0 +
                               lin.bias.numpy(), rtol=1e-5, atol=1e-5)
    # grads flow to g and v through the derived weight
    loss = (y * y).sum()
    loss.backward()
    assert lin.weight_g.grad is not None and lin.weight_v.grad is not None

    nn.utils.remove_weight_norm(lin, "weight")
    names = [n for n, _ in lin.named_parameters()]
    assert "weight" in names and "weight_g" not in names
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5,
                               atol=1e-6)


def test_weight_norm_scales_norm():
    pt.seed(0)
    lin = nn.Linear(5, 3)
    nn.utils.weight_norm(lin, "weight", dim=1)  # per-output-col norms
    # doubling g doubles the effective weight column norms
    lin.weight_g.value = lin.weight_g.value * 2.0
    x = pt.to_tensor(np.eye(5, dtype="f"))
    y = lin(x) - lin.bias
    norms = np.linalg.norm(np.asarray(y.value), axis=0)
    # the effective per-column norm equals the (doubled) g
    np.testing.assert_allclose(
        norms, np.asarray(lin.weight_g.value).ravel(), rtol=1e-4)


def test_spectral_norm_unit_sigma():
    pt.seed(0)
    lin = nn.Linear(8, 8)
    nn.utils.spectral_norm(lin, "weight", n_power_iterations=20)
    for _ in range(10):  # power iteration converges across calls
        lin(pt.to_tensor(np.zeros((1, 8), "f")))
    w = np.asarray(lin.weight.value if hasattr(lin.weight, "value")
                   else lin.weight)
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, rtol=1e-3)


def test_vector_param_roundtrip():
    pt.seed(0)
    lin = nn.Linear(4, 3)
    params = list(lin.parameters())
    vec = nn.utils.parameters_to_vector(params)
    assert np.asarray(vec.value).shape == (4 * 3 + 3,)
    doubled = vec * 2.0
    nn.utils.vector_to_parameters(doubled, params)
    got = nn.utils.parameters_to_vector(list(lin.parameters()))
    np.testing.assert_allclose(np.asarray(got.value),
                               np.asarray(vec.value) * 2.0, rtol=1e-6)


def test_new_layer_wrappers_forward():
    rng = np.random.default_rng(0)
    x4 = pt.to_tensor(rng.standard_normal((1, 2, 6, 6)).astype("f"))
    x5 = pt.to_tensor(rng.standard_normal((1, 2, 3, 4, 5)).astype("f"))

    assert nn.Pad3D([1, 1, 2, 2, 0, 1])(x5).shape == (1, 2, 4, 8, 7)
    assert nn.ZeroPad2D([1, 2, 3, 4])(x4).shape == (1, 2, 13, 9)
    cols = nn.Unfold(2, strides=2)(x4)
    assert cols.shape == (1, 2 * 4, 9)
    back = nn.Fold((6, 6), 2, strides=2)(cols)
    np.testing.assert_allclose(np.asarray(back.value),
                               np.asarray(x4.value), rtol=1e-6)
    assert nn.LPPool2D(2.0, 2, 2)(x4).shape == (1, 2, 3, 3)
    out = nn.ThresholdedReLU(0.5)(x4)
    got = np.asarray(out.value)
    assert ((got == 0) | (got > 0.5)).all()

    inp = pt.to_tensor(rng.standard_normal((4, 5)).astype("f"))
    sign = pt.to_tensor(np.sign(rng.standard_normal((4, 5))).astype("f"))
    y01 = pt.to_tensor((rng.random((4, 5)) > 0.5).astype("f"))
    lam = pt.to_tensor((np.abs(rng.standard_normal((4, 5))) + 0.5)
                       .astype("f"))
    var = pt.to_tensor((np.abs(rng.standard_normal((4, 5))) + 0.1)
                       .astype("f"))
    for loss in (nn.SoftMarginLoss()(inp, sign),
                 nn.MultiLabelSoftMarginLoss()(inp, y01),
                 nn.PoissonNLLLoss()(inp, lam),
                 nn.GaussianNLLLoss()(inp, lam, var)):
        assert np.isfinite(float(loss))


def test_data_norm():
    """data_norm op formula + DataNorm layer stat accumulation
    (reference operators/data_norm_op.cc semantics: normalize from
    ACCUMULATED batch statistics, heavy prior decays slowly)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 4)).astype("f") * 3 + 1

    from paddle_tpu.ops.nn_functional import data_norm

    bsize = np.full((4,), 100.0, "f")
    bsum = np.full((4,), 200.0, "f")   # mean 2
    bss = np.full((4,), 400.0, "f")    # centered: var 4 -> scale 0.5
    got = np.asarray(data_norm(x, bsize, bsum, bss))
    np.testing.assert_allclose(got, (x - 2.0) * 0.5, rtol=1e-4, atol=1e-4)

    dn = nn.DataNorm(4)
    dn.train()
    s0 = np.asarray(dn.batch_sum.value).copy()
    out = dn(pt.Tensor(x))
    assert out.shape == (32, 4)
    # accumulators moved toward the batch stats
    assert (np.asarray(dn.batch_sum.value) != s0).all()
    np.testing.assert_allclose(
        np.asarray(dn.batch_sum.value) - s0 * (1 - 7e-7) - s0 * 7e-7,
        x.sum(0), rtol=1e-3, atol=2e-3)
    import pytest as _pytest
    with _pytest.raises(NotImplementedError):
        nn.DataNorm(4, slot_dim=8)
    # eval mode: stats frozen
    dn.eval()
    s1 = np.asarray(dn.batch_sum.value).copy()
    dn(pt.Tensor(x))
    np.testing.assert_array_equal(np.asarray(dn.batch_sum.value), s1)
