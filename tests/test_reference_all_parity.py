"""Full public-API parity against the reference's __all__ exports.

Walks the reference tree's ``__all__`` lists (python/paddle/**/__init__.py)
and asserts every name exists in the corresponding paddle_tpu module. This
is the API.spec-style freeze (reference: paddle/fluid/API.spec) taken to
the whole surface: a missing name is a regression.
"""

import ast
import importlib
import os

import pytest

REF = "/root/reference/python/paddle"

MODULES = {
    "paddle": "__init__.py",
    "paddle.nn": "nn/__init__.py",
    "paddle.nn.functional": "nn/functional/__init__.py",
    "paddle.nn.initializer": "nn/initializer/__init__.py",
    "paddle.tensor": "tensor/__init__.py",
    "paddle.optimizer": "optimizer/__init__.py",
    "paddle.static": "static/__init__.py",
    "paddle.static.nn": "static/nn/__init__.py",
    "paddle.io": "io/__init__.py",
    "paddle.jit": "jit/__init__.py",
    "paddle.metric": "metric/__init__.py",
    "paddle.amp": "amp/__init__.py",
    "paddle.vision": "vision/__init__.py",
    "paddle.vision.ops": "vision/ops.py",
    "paddle.vision.transforms": "vision/transforms/__init__.py",
    "paddle.vision.models": "vision/models/__init__.py",
    "paddle.vision.datasets": "vision/datasets/__init__.py",
    "paddle.text": "text/__init__.py",
    "paddle.distributed": "distributed/__init__.py",
    "paddle.distributed.fleet": "distributed/fleet/__init__.py",
    "paddle.distribution": "distribution.py",
    "paddle.utils": "utils/__init__.py",
    "paddle.autograd": "autograd/__init__.py",
    "paddle.device": "device.py",
    "paddle.inference": "inference/__init__.py",
    "paddle.regularizer": "regularizer.py",
    "paddle.hub": "hub.py",
    "paddle.onnx": "onnx/__init__.py",
    "paddle.incubate": "incubate/__init__.py",
    "paddle.sysconfig": "sysconfig.py",
}


def _collect_all(path):
    try:
        tree = ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return []
    names = []
    for node in ast.walk(tree):
        value = None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    value = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name) and node.target.id == "__all__":
            value = node.value
        if value is not None:
            try:
                names += [n for n in ast.literal_eval(value)
                          if isinstance(n, str)]
            except ValueError:
                pass
    return names


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference tree not present")
@pytest.mark.parametrize("ref_mod,rel", sorted(MODULES.items()))
def test_all_names_present(ref_mod, rel):
    path = os.path.join(REF, rel)
    ref_names = set(_collect_all(path))
    if not ref_names:
        pytest.skip(f"{rel} has no __all__")
    ours = importlib.import_module(
        ref_mod.replace("paddle", "paddle_tpu", 1))
    missing = sorted(n for n in ref_names if not hasattr(ours, n))
    assert not missing, (
        f"{ref_mod}: {len(missing)}/{len(ref_names)} reference __all__ "
        f"names missing: {missing}")


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference tree not present")
def test_signature_parity_frozen():
    """Parameter-name parity for the audited public surface: a param the
    reference accepts that we don't means reference user code raises
    TypeError (tools/signature_parity.py)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        from signature_parity import audit
    finally:
        sys.path.pop(0)
    findings = audit()
    assert not findings, findings


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference tree not present")
def test_tensor_method_parity():
    """Every method the reference patches onto Tensor
    (tensor/__init__.py import list + varbase_patch_methods.py) exists on
    our Tensor, except names that are actually free functions / static
    graph plumbing."""
    import re

    import numpy as np

    import paddle_tpu as pt

    names = set()
    tree = ast.parse(open(os.path.join(REF, "tensor/__init__.py")).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                names.add(a.asname or a.name)
    vb = open(os.path.join(
        REF, "fluid/dygraph/varbase_patch_methods.py")).read()
    for m in re.finditer(r'\("([a-z_0-9]+)",', vb):
        names.add(m.group(1))

    # free functions / creation APIs / static-graph (LoDTensorArray)
    # plumbing the reference lists alongside methods but never calls
    # through a tensor receiver
    not_methods = {
        "arange", "empty", "eye", "full", "linspace", "meshgrid", "ones",
        "zeros", "rand", "randn", "randint", "randperm", "normal",
        "uniform", "standard_normal", "to_tensor", "set_printoptions",
        "is_tensor", "broadcast_shape", "add_n", "concat", "where",
        "multiplex", "scatter_nd", "create_array", "array_length",
        "array_read", "array_write", "gradient", "inplace_version",
        "block",
    }
    t = pt.to_tensor(np.ones((2, 2), "float32"))
    missing = sorted(n for n in names
                     if not n.startswith("_") and n not in not_methods
                     and not hasattr(t, n))
    assert not missing, missing
    # the method-flavored extras exist too
    for extra in ("gradient", "inplace_version", "block", "where",
                  "sqrt_", "clip_", "flatten_"):
        assert hasattr(t, extra), extra
