"""OpTest harness.

TPU-native equivalent of the reference's declarative op-test fixture
(reference: python/paddle/fluid/tests/unittests/op_test.py:270 OpTest,
check_output:1076, check_grad:1405 with numeric finite-difference gradients
get_numeric_gradient:110). Here:

- ``check_forward``: eager wrapped op vs a NumPy reference, and the same
  kernel under jax.jit (traced path) — covering the reference's
  dygraph/static parity checks.
- ``check_grad``: the eager tape's backward vs jax.grad of the pure kernel
  (exact agreement) and finite-difference verification via
  jax.test_util.check_grads.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.ops.registry import get_op
from paddle_tpu.tensor import Tensor


def _to_np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.value)
    return np.asarray(x)


def check_forward(name, np_ref, *args, rtol=1e-5, atol=1e-6, check_jit=True,
                  **kwargs):
    """Run wrapped op eagerly and (optionally) jitted; compare to np_ref."""
    opdef = get_op(name)
    wrapped = pt.dispatch.wrap_op(name)
    t_args = [pt.to_tensor(a) if isinstance(a, np.ndarray) else a
              for a in args]
    out_eager = wrapped(*t_args, **kwargs)
    expect = np_ref(*args, **kwargs)

    def compare(got, exp, mode):
        got_leaves = jax.tree_util.tree_leaves(
            got, is_leaf=lambda x: isinstance(x, Tensor))
        exp_leaves = jax.tree_util.tree_leaves(exp)
        assert len(got_leaves) == len(exp_leaves), \
            f"{name} [{mode}]: arity {len(got_leaves)} vs {len(exp_leaves)}"
        for g, e in zip(got_leaves, exp_leaves):
            np.testing.assert_allclose(
                _to_np(g), np.asarray(e), rtol=rtol, atol=atol,
                err_msg=f"op={name} mode={mode}")

    compare(out_eager, expect, "eager")
    if check_jit and not opdef.dynamic_shape:
        raw_args = [jnp.asarray(a) if isinstance(a, np.ndarray) else a
                    for a in args]
        jitted = jax.jit(lambda *xs: opdef.fn(*xs, **kwargs))
        try:
            out_jit = jitted(*raw_args)
        except Exception as e:  # pragma: no cover - surface as test failure
            raise AssertionError(f"op={name} failed under jit: {e}") from e
        compare(out_jit, expect, "jit")
    return out_eager


def check_grad(name, *args, arg_idx=(0,), rtol=1e-4, atol=1e-5,
               numeric=False, order=1, **kwargs):
    """Compare eager-tape grads against jax.grad of the pure kernel."""
    opdef = get_op(name)
    raw_args = [jnp.asarray(a, dtype=jnp.float32)
                if isinstance(a, np.ndarray) else a for a in args]

    # tape path
    t_args = [Tensor(r, stop_gradient=(i not in arg_idx))
              if isinstance(r, jax.Array) else r
              for i, r in enumerate(raw_args)]
    wrapped = pt.dispatch.wrap_op(name)
    out = wrapped(*t_args, **kwargs)
    first = jax.tree_util.tree_leaves(
        out, is_leaf=lambda x: isinstance(x, Tensor))[0]
    loss = first.sum() if first.shape else first
    loss.backward()

    # functional path
    def f(*dvals):
        full = list(raw_args)
        for i, v in zip(arg_idx, dvals):
            full[i] = v
        o = opdef.fn(*full, **kwargs)
        lead = jax.tree_util.tree_leaves(o)[0]
        return jnp.sum(lead)

    primals = [raw_args[i] for i in arg_idx]
    expected = jax.grad(f, argnums=tuple(range(len(primals))))(*primals)
    for i, exp in zip(arg_idx, expected):
        got = t_args[i].grad
        assert got is not None, f"op={name}: no grad for arg {i}"
        np.testing.assert_allclose(_to_np(got), np.asarray(exp), rtol=rtol,
                                   atol=atol, err_msg=f"op={name} arg={i}")
    if numeric:
        from jax.test_util import check_grads as jax_check_grads
        jax_check_grads(f, tuple(primals), order=order, modes=("rev",),
                        rtol=0.05, atol=0.05)
