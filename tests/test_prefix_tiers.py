"""Hierarchical prefix cache (r15): host-RAM/disk spill tiers under
the refcounted prefix cache, restore via device_put + page-table
splice, and cache-affinity routing in the failover router.

The contracts pinned here (ISSUE r15 acceptance):

- greedy outputs are BIT-IDENTICAL with spill tiers on vs off across
  the restore-hit, partial-chain-hit and miss paths (fp + paged_int8,
  with chunked prefill and speculative decoding riding along), and
  restored int8 pages are byte-equal to the evicted blob;
- every restore-unwind path (deadline expiry, close(), resurrection)
  releases the restored pages with zero leaks and zero dangling tier
  blobs after drain;
- a corrupt blob (seeded ``cache.spill`` "torn" fault) is a typed,
  counted fallback to chained prefill — never wrong tokens;
- the router's affinity steering lands keyed requests on the replica
  advertising their first-block prefix key and NEVER blocks failover.
"""

import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed import fault_inject as fi
from paddle_tpu.distributed.resilience import NO_RETRY_SITES
from paddle_tpu.inference import (PageAllocator, SpeculativeConfig,
                                  create_decode_engine)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (DiskSpillTier, HostSpillTier,
                                PrefixCache, ServingMetrics,
                                ServingServer, SpillCorrupt,
                                client_request)
from paddle_tpu.serving.prefix_cache import (pack_page_blob,
                                             unpack_page_blob)
from paddle_tpu.serving.supervisor import FailoverRouter


@pytest.fixture(autouse=True)
def _clean_injector():
    fi.reset()
    yield
    fi.reset()


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests."""
    yield


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


ENGINE_KW = dict(num_slots=2, page_size=8, max_seq_len=96, num_pages=12)


def _engine(m, **kw):
    merged = dict(ENGINE_KW)
    merged.update(kw)
    return create_decode_engine(m, **merged)


def _prompts(shared_len=19, tails=(3, 5, 7, 9)):
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 100, (shared_len,)).astype(np.int32)
    return [np.concatenate([shared,
                            rng.integers(0, 100, (t,)).astype(np.int32)])
            for t in tails]


def _baseline(model, prompts, mnt=6, **kw):
    eng = _engine(model, **kw)
    out = []
    for p in prompts:
        rid = eng.submit(p, max_new_tokens=mnt)
        out.append(eng.run()[rid])
    eng.close()
    return out


# ---------------------------------------------------------------------------
# Blob format (no jax)
# ---------------------------------------------------------------------------

class TestBlobFormat:
    def _layers(self, int8=False, nl=3, shape=(8, 2, 4)):
        rng = np.random.default_rng(0)
        out = []
        for _ in range(nl):
            if int8:
                k = rng.integers(-128, 127, shape).astype(np.int8)
                v = rng.integers(-128, 127, shape).astype(np.int8)
                ks = rng.random(shape[:2]).astype(np.float32)
                vs = rng.random(shape[:2]).astype(np.float32)
            else:
                k = rng.random(shape).astype(np.float32)
                v = rng.random(shape).astype(np.float32)
                ks = vs = None
            out.append((k, v, ks, vs))
        return out

    @pytest.mark.parametrize("int8", [False, True])
    def test_roundtrip_byte_exact(self, int8):
        layers = self._layers(int8=int8)
        back = unpack_page_blob(pack_page_blob(layers))
        assert len(back) == len(layers)
        for (a, b) in zip(layers, back):
            for x, y in zip(a, b):
                if x is None:
                    assert y is None
                    continue
                assert x.dtype == y.dtype and x.shape == y.shape
                assert x.tobytes() == y.tobytes()

    def test_corruption_is_typed(self):
        blob = pack_page_blob(self._layers())
        flipped = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        with pytest.raises(SpillCorrupt):
            unpack_page_blob(flipped)
        with pytest.raises(SpillCorrupt):
            unpack_page_blob(blob[: len(blob) // 2])  # truncated
        with pytest.raises(SpillCorrupt):
            unpack_page_blob(b"XXXX" + blob[4:])  # bad magic
        with pytest.raises(SpillCorrupt):
            unpack_page_blob(b"")


# ---------------------------------------------------------------------------
# Tier semantics (no jax)
# ---------------------------------------------------------------------------

class TestSpillTiers:
    def test_host_lru_byte_budget_eviction_order(self):
        t = HostSpillTier(100)
        t.put(b"a", b"x" * 40)
        t.put(b"b", b"y" * 40)
        assert t.get(b"a") is not None  # refresh a: b becomes LRU
        t.put(b"c", b"z" * 40)  # over budget -> b (LRU) dropped
        assert t.contains(b"a") and t.contains(b"c")
        assert not t.contains(b"b")
        assert t.dropped_blobs == 1
        assert t.occupancy_bytes == 80
        t.check_consistent()

    def test_host_demotes_into_disk(self, tmp_path):
        disk = DiskSpillTier(str(tmp_path), 1000)
        host = HostSpillTier(50, next_tier=disk)
        host.put(b"a", b"x" * 40)
        host.put(b"b", b"y" * 40)  # a demoted to disk, not dropped
        assert not host.contains(b"a") and disk.contains(b"a")
        assert host.demoted_blobs == 1 and host.dropped_blobs == 0
        assert disk.get(b"a") == b"x" * 40
        # oversize blob skips the host tier entirely
        host.put(b"c", b"z" * 80)
        assert not host.contains(b"c") and disk.contains(b"c")
        for t in (host, disk):
            t.check_consistent()
        disk.clear()
        assert disk.blob_count == 0
        assert not any(f.endswith(".kvblob")
                       for f in os.listdir(str(tmp_path)))

    def test_disk_scrubs_stale_blobs_and_audits_dangling(self, tmp_path):
        (tmp_path / "deadbeef.kvblob").write_bytes(b"stale")
        disk = DiskSpillTier(str(tmp_path), 1000)
        # a previous process's blobs never survive into a new tier
        assert disk.blob_count == 0
        assert not (tmp_path / "deadbeef.kvblob").exists()
        disk.put(b"k", b"blob")
        disk.check_consistent()
        (tmp_path / "dangling.kvblob").write_bytes(b"x")
        with pytest.raises(RuntimeError, match="dangling"):
            disk.check_consistent()

    def test_disk_vanished_file_degrades_to_miss(self, tmp_path):
        disk = DiskSpillTier(str(tmp_path), 1000)
        disk.put(b"k", b"blob")
        os.unlink(disk._path(b"k"))
        assert disk.get(b"k") is None  # miss, not a crash
        assert not disk.contains(b"k")

    def test_last_tier_budget_eviction_survives_vanished_file(
            self, tmp_path):
        """A last-tier LRU eviction is a pure drop (no read), and a
        vanished backing file must not raise into the engine's
        eviction path or corrupt the occupancy books."""
        disk = DiskSpillTier(str(tmp_path), 100)
        disk.put(b"a", b"x" * 60)
        os.unlink(disk._path(b"a"))
        disk.put(b"b", b"y" * 60)  # evicts a: file already gone
        assert disk.contains(b"b") and not disk.contains(b"a")
        assert disk.occupancy_bytes == 60
        disk.check_consistent()


# ---------------------------------------------------------------------------
# Cache-level spill/restore semantics (fake device IO, no model)
# ---------------------------------------------------------------------------

class _FakeIO:
    """Deterministic per-page fake device content: page p, layer l
    holds the constant p*10+l — enough to verify which blob lands
    where without a model."""

    def __init__(self):
        self.reads = 0
        self.spliced = {}  # dest page -> source constant

    def read_page(self, page):
        self.reads += 1
        return [(np.full((4, 2, 3), page * 10 + l, np.float32),
                 np.full((4, 2, 3), page * 10 + l, np.float32),
                 None, None) for l in range(2)]

    def splice_page(self, pages, layers_list):
        self.calls = getattr(self, "calls", 0) + 1
        for p, layers in zip(pages, layers_list):
            self.spliced[p] = float(layers[0][0].flat[0])


def _unit_cache(**kw):
    pc = PrefixCache(4, **kw)
    io = _FakeIO()
    pc.attach_device_io(io.read_page, io.splice_page)
    return pc, io


class TestCacheSpillRestore:
    def _seed(self, pc, alloc, prompt):
        """Insert prompt's shareable chain, release, evict all (spill).
        Eviction is leaf-first, so spill order is chain-REVERSED.
        Returns (chain keys, the original page-table row)."""
        n = pc._shareable_blocks(prompt)
        pages = alloc.alloc("req", n + 1)
        row = np.array(pages, dtype=np.int32)
        keys = pc.insert(prompt, row, alloc, "req", 4, ())
        pc.release(keys)
        alloc.free("req")
        assert pc.evict_until(alloc, alloc.num_pages)
        return keys, row

    def test_evict_spills_then_restore_reallocates(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)  # 3 shareable blocks
        keys, row = self._seed(pc, alloc, prompt)
        assert pc.spilled_pages == 3
        assert pc.tiers[0].blob_count == 3
        # restore the whole chain into fresh pages
        mk, mp = pc.match(prompt)
        assert mk == ()
        rkeys, rpages, info = pc.restore_from_spill(prompt, mk, alloc)
        assert rkeys == keys and len(rpages) == 3
        assert info["host"] == 3 and info["ms"] > 0
        assert pc.tier_hit_pages["host"] == 3
        # each restored page got ITS original page's content spliced
        # in chain order (the fake reads page p as the constant p*10)
        assert [io.spliced[p] for p in rpages] == \
            [float(row[i] * 10) for i in range(3)]
        # the whole 3-page run restored in ONE batched splice call
        assert io.calls == 1
        # restored entries are regular device entries: match hits now
        mk2, mp2 = pc.match(prompt)
        assert mk2 == keys and mp2 == rpages
        pc.acquire(rkeys)
        pc.release(rkeys)
        pc.clear(alloc)
        alloc.check_no_leak()
        assert pc.tiers[0].blob_count == 0  # zero dangling blobs

    def test_mid_chain_tier_miss_stops_restore(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys, _ = self._seed(pc, alloc, prompt)
        pc.tiers[0].remove(keys[1])  # hole in the middle of the chain
        rkeys, rpages, _ = pc.restore_from_spill(prompt, (), alloc)
        assert rkeys == keys[:1]  # contiguous prefix only
        pc.clear(alloc)
        alloc.check_no_leak()

    def test_corrupt_blob_is_typed_counted_and_removed(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys, _ = self._seed(pc, alloc, prompt)
        t = pc.tiers[0]
        blob = t._load(keys[0])
        t._blobs[keys[0]] = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        rkeys, _, info = pc.restore_from_spill(prompt, (), alloc)
        assert rkeys == ()  # nothing spliced past a corrupt head
        assert pc.restore_corrupt == 1 and info["corrupt"] == 1
        assert not t.contains(keys[0])  # poisoned blob dropped
        assert not io.spliced
        pc.clear(alloc)
        alloc.check_no_leak()

    def test_cache_spill_fault_write_and_read_sides(self):
        # write side: an armed abort loses the blob (counted), the
        # eviction itself still succeeds
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        fi.get_injector().arm("cache.spill", at_calls=[1])
        self._seed(pc, alloc, prompt)
        assert pc.spill_failed == 1
        assert pc.tiers[0].blob_count == 2  # calls 2,3 spilled fine
        fi.reset()
        # read side: an armed abort on restore degrades to a miss
        fi.get_injector().arm("cache.spill", probability=1.0)
        rkeys, _, _ = pc.restore_from_spill(prompt, (), alloc)
        assert rkeys == () and pc.spill_failed == 2
        fi.reset()
        pc.clear(alloc)
        alloc.check_no_leak()

    def test_torn_spill_write_caught_by_crc_on_restore(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        fi.get_injector().arm("cache.spill", at_calls=[1], mode="torn")
        keys, _ = self._seed(pc, alloc, prompt)
        assert pc.tiers[0].blob_count == 3  # torn blob WAS stored
        fi.reset()
        rkeys, _, info = pc.restore_from_spill(prompt, (), alloc)
        # eviction is leaf-first, so the torn first spill is the chain
        # TAIL: the head restores fine, crc trips at the tail and the
        # chained-prefill fallback owns the rest
        assert rkeys == keys[:2] and info["corrupt"] == 1
        assert pc.restore_corrupt == 1
        pc.clear(alloc)
        alloc.check_no_leak()

    def test_reeviction_of_restored_page_is_a_touch_not_a_reread(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(9, dtype=np.int32)  # 2 shareable blocks
        self._seed(pc, alloc, prompt)
        reads = io.reads
        rkeys, _, _ = pc.restore_from_spill(prompt, (), alloc)
        assert len(rkeys) == 2
        pc.evict_until(alloc, alloc.num_pages)  # evict the restored
        # inclusive tiers: the blob is still there, so re-eviction
        # refreshed LRU without a second device read
        assert io.reads == reads
        assert pc.tiers[0].blob_count == 2
        pc.clear(alloc)
        alloc.check_no_leak()

    def test_advertised_keys_cover_device_and_tiers(self):
        pc, io = _unit_cache(spill_bytes=1 << 20)
        alloc = PageAllocator(8)
        prompt = np.arange(13, dtype=np.int32)
        keys, _ = self._seed(pc, alloc, prompt)
        # everything evicted to the host tier: the head key is still
        # advertised (restorable == steerable)
        assert keys[0].hex() in pc.advertised_keys()
        pc.tiers[0].clear()
        assert keys[0].hex() not in pc.advertised_keys()  # pruned
        pc.clear(alloc)

    def test_site_registered_with_disposition(self):
        assert "cache.spill" in fi.FAULT_SITES
        assert "cache.spill" in NO_RETRY_SITES


# ---------------------------------------------------------------------------
# Engine integration: bit-identity + byte-equality + leak audits
# ---------------------------------------------------------------------------

class TestEngineRestore:
    def _force_spill(self, eng):
        pc = eng._prefix_cache
        assert pc.evict_until(eng.allocator, eng.allocator.num_pages)
        return pc

    def test_restore_partial_and_miss_paths_bit_identical_fp(self, model):
        prompts = _prompts()
        base = _baseline(model, prompts)
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc)
        try:
            # MISS path: tiers on, nothing spilled yet
            for p, b in zip(prompts, base):
                rid = eng.submit(p, max_new_tokens=6)
                assert np.array_equal(eng.run()[rid], b)
            self._force_spill(eng)
            spilled = pc.spilled_pages
            assert spilled > 0
            # RESTORE-HIT path: full chain comes back from the host tier
            rid = eng.submit(prompts[0], max_new_tokens=6)
            assert np.array_equal(eng.run()[rid], base[0])
            assert pc.restored_pages > 0
            assert pc.tier_hit_pages["host"] > 0
            # PARTIAL-CHAIN-HIT path: drop the chain's tail blobs so
            # only a prefix restores; the rest rides chained prefill
            self._force_spill(eng)
            chain = pc._chain_keys(prompts[1])
            for key, _parent, _blk in chain[1:]:
                pc.tiers[0].remove(key)
            before = pc.restored_pages
            rid = eng.submit(prompts[1], max_new_tokens=6)
            assert np.array_equal(eng.run()[rid], base[1])
            assert pc.restored_pages == before + 1  # head only
            pc.check_consistent(eng.allocator)
        finally:
            eng.close()
        assert all(t.blob_count == 0 for t in pc.tiers)

    def test_restored_int8_pages_byte_equal_to_blob(self, model):
        prompts = _prompts()
        base = _baseline(model, prompts[:2], kv_int8=True)
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc, kv_int8=True)
        try:
            for p, b in zip(prompts[:2], base):
                rid = eng.submit(p, max_new_tokens=6)
                assert np.array_equal(eng.run()[rid], b)
            self._force_spill(eng)
            blobs = {k: pc.tiers[0]._load(k)
                     for k in list(pc.tiers[0]._index)}
            rid = eng.submit(prompts[0], max_new_tokens=6)
            assert np.array_equal(eng.run()[rid], base[0])
            assert pc.restored_pages > 0
            # byte-equality: every restored page's device content
            # re-reads EXACTLY as the blob it came from
            for key, ent in pc._entries.items():
                if key not in blobs:
                    continue
                now = eng._read_page(ent.page)
                packed = unpack_page_blob(blobs[key])
                for a, b in zip(now, packed):
                    for x, y in zip(a, b):
                        if x is None:
                            assert y is None
                            continue
                        assert x.tobytes() == y.tobytes()
            pc.check_consistent(eng.allocator)
        finally:
            eng.close()

    def test_restore_with_chunked_prefill_bit_identical(self, model):
        prompts = _prompts()
        base = _baseline(model, prompts)
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc, prefill_chunk_tokens=8)
        try:
            for p, b in zip(prompts, base):
                rid = eng.submit(p, max_new_tokens=6)
                assert np.array_equal(eng.run()[rid], b)
            self._force_spill(eng)
            rid = eng.submit(prompts[0], max_new_tokens=6)
            assert np.array_equal(eng.run()[rid], base[0])
            assert pc.restored_pages > 0
            pc.check_consistent(eng.allocator)
        finally:
            eng.close()

    def test_restore_with_speculative_bit_identical(self, model):
        prompts = _prompts()
        base = _baseline(model, prompts[:2])
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc,
                      speculative=SpeculativeConfig(k=2))
        try:
            for p, b in zip(prompts[:2], base):
                rid = eng.submit(p, max_new_tokens=6)
                assert np.array_equal(eng.run()[rid], b)
            self._force_spill(eng)
            rid = eng.submit(prompts[0], max_new_tokens=6)
            assert np.array_equal(eng.run()[rid], base[0])
            assert pc.restored_pages > 0
            pc.check_consistent(eng.allocator)
        finally:
            eng.close()

    def test_disk_tier_budget_lru_demotion_end_to_end(self, model,
                                                      tmp_path):
        """A host tier too small for the working set demotes LRU blobs
        to disk; a restore that misses host falls through to disk."""
        prompts = _prompts()
        base = _baseline(model, prompts)
        # one gpt_tiny fp page blob is ~16KiB (4 layers x 2 pools x
        # 8x4x16 f32); host holds ~2 blobs, disk the overflow
        pc = PrefixCache(8, spill_bytes=40_000,
                         spill_dir=str(tmp_path), disk_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc)
        try:
            for p, b in zip(prompts, base):
                rid = eng.submit(p, max_new_tokens=6)
                assert np.array_equal(eng.run()[rid], b)
            self._force_spill(eng)
            host, disk = pc.tiers
            assert host.occupancy_bytes <= host.capacity_bytes
            assert disk.blob_count > 0, "expected LRU demotion to disk"
            rid = eng.submit(prompts[0], max_new_tokens=6)
            assert np.array_equal(eng.run()[rid], base[0])
            assert (pc.tier_hit_pages["host"]
                    + pc.tier_hit_pages["disk"]) > 0
            pc.check_consistent(eng.allocator)
        finally:
            eng.close()
        assert not any(f.endswith(".kvblob")
                       for f in os.listdir(str(tmp_path)))

    def test_torn_spill_falls_back_to_prefill_same_tokens(self, model):
        prompts = _prompts()
        base = _baseline(model, prompts[:2])
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc)
        try:
            for p, b in zip(prompts[:2], base):
                rid = eng.submit(p, max_new_tokens=6)
                assert np.array_equal(eng.run()[rid], b)
            fi.get_injector().arm("cache.spill", probability=1.0,
                                  mode="torn", seed=3)
            self._force_spill(eng)
            fi.reset()
            rid = eng.submit(prompts[0], max_new_tokens=6)
            # every blob is corrupt: crc trips, chained prefill
            # recomputes — tokens STILL bit-identical, failure typed
            assert np.array_equal(eng.run()[rid], base[0])
            assert pc.restore_corrupt > 0
            assert pc.restored_pages == 0
            pc.check_consistent(eng.allocator)
        finally:
            eng.close()

    def test_restore_unwind_deadline_and_close_zero_leak(self, model):
        prompts = _prompts()
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc)
        try:
            rid = eng.submit(prompts[0], max_new_tokens=6)
            eng.run()
            self._force_spill(eng)
            # deadline already expired at admission: the engine sheds
            # it typed before any restore work is spent
            eng.submit(prompts[0], max_new_tokens=6,
                       deadline_t=time.monotonic() - 1.0)
            eng.step()
            # restore-hit request evicted mid-flight by a deadline:
            # admit with a generous budget (so the deadline-hopeless
            # gate can't shed it before the restore — host-load
            # dependent), then expire it DETERMINISTICALLY via the
            # sweep's now= knob: the restored pages are cache-owned
            # and survive, the request's pins release, books balance
            rid = eng.submit(prompts[0], max_new_tokens=50,
                             deadline_t=time.monotonic() + 60.0)
            eng.step()  # admission restores + first token
            assert pc.restored_pages > 0
            expired = eng.expire_deadlines(now=time.monotonic() + 61.0)
            assert [r.req_id for r in expired] == [rid]
            assert expired[0].state == "deadline"
            pc.check_consistent(eng.allocator)
            # close() mid-flight with a restored chain pinned
            rid = eng.submit(prompts[0], max_new_tokens=6)
            eng.step()
        finally:
            eng.close()  # asserts check_no_leak internally
        assert all(t.blob_count == 0 for t in pc.tiers)

    def test_resurrection_with_spill_tiers_zero_leak(self, model):
        """Engine death mid-decode with spill tiers configured: the
        rebuilt engine carries the SAME tier config (fresh, empty
        tiers — the old cache's blobs are scrubbed by close()), the
        replay is bit-identical, and the books balance after drain."""
        prompts = [list(range(1, 7)), list(range(3, 12))]
        exp = _baseline(model, [np.asarray(p, np.int32)
                                for p in prompts], mnt=8)
        fi.get_injector().arm("engine.step", at_calls=[3, 4])
        srv = ServingServer(model, spill_bytes=1 << 20,
                            max_engine_errors=2,
                            metrics=ServingMetrics(
                                registry=StatRegistry()),
                            **ENGINE_KW)
        port = srv.start()
        results = [None, None]

        def client(i):
            results[i] = client_request(
                "127.0.0.1", port,
                {"op": "generate", "prompt": prompts[i],
                 "max_new_tokens": 8}, timeout_s=180)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        for i in range(2):
            assert results[i] is not None and \
                "error" not in results[i], results[i]
            assert results[i]["tokens"] == [int(t) for t in exp[i]]
        assert srv._restarts == 1
        # the resurrected engine's cache still carries spill tiers
        # (the recipe preserved the config)
        assert srv.prefix_cache.tiers and \
            srv.prefix_cache.tiers[0].name == "host"
        chk = client_request("127.0.0.1", port, {"op": "leak_check"})
        assert chk["ok"], chk
        srv.stop()
        srv.prefix_cache.check_consistent(srv.engine.allocator)
        assert all(t.blob_count == 0
                   for t in srv.prefix_cache.tiers)

    def test_stats_and_metrics_surfaces(self, model):
        prompts = _prompts()
        met = ServingMetrics(registry=StatRegistry())
        pc = PrefixCache(8, spill_bytes=1 << 20)
        eng = _engine(model, prefix_cache=pc,
                      on_complete=met.observe_request)
        try:
            for p in prompts:
                eng.submit(p, max_new_tokens=4)
            eng.run()
            self._force_spill(eng)
            eng.submit(prompts[0], max_new_tokens=4)
            eng.run()
            counters = met.snapshot()["counters"]
            assert counters["cache_restored_pages_total"] > 0
            assert counters["cache_host_hit_pages_total"] > 0
            text = met.prometheus_text()
            assert "serving_restore_ms_bucket" in text
            assert "serving_cache_restored_pages_total" in text
            # per-tier stats surface
            ts = pc.tier_stats()
            assert set(ts) == {"device", "host"}
            assert ts["host"]["hit_pages"] > 0
            assert pc.hit_rate() > 0
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# Router cache-affinity steering
# ---------------------------------------------------------------------------

class _StubReplica:
    def __init__(self, idx, port=0, page_size=8, load=0, keys=()):
        self.idx = idx
        self.port = port
        self.ready = True
        self.restarts = 0
        self.page_size = page_size
        self.load = load
        self.prefix_keys = frozenset(keys)

    def alive(self):
        return True


class _StubSup:
    def __init__(self, reps, host="127.0.0.1"):
        self.replicas = reps
        self.host = host

    def live(self):
        return [r for r in self.replicas if r.ready]


def _first_block_key(prompt, page_size=8):
    from paddle_tpu.serving.prefix_cache import _block_hash
    return _block_hash(None, np.asarray(prompt[:page_size],
                                        np.int32)).hex()


class TestRouterAffinity:
    def _router(self, reps):
        return FailoverRouter(_StubSup(reps))

    def test_advertising_holder_wins(self):
        prompt = list(range(20))
        key = _first_block_key(prompt)
        reps = [_StubReplica(0), _StubReplica(1, keys=[key]),
                _StubReplica(2)]
        router = self._router(reps)
        msg = {"prompt": prompt, "key": "k"}
        ak = router._affinity_key(msg)
        assert ak == key
        for _ in range(4):  # deterministic, not round-robin
            assert router._pick(set(), affinity_key=ak).idx == 1
        assert router.affinity_hits_total == 4

    def test_holder_ties_break_least_loaded(self):
        prompt = list(range(20))
        key = _first_block_key(prompt)
        reps = [_StubReplica(0, load=5, keys=[key]),
                _StubReplica(1, load=1, keys=[key])]
        router = self._router(reps)
        assert router._pick(set(), affinity_key=key).idx == 1

    def test_rendezvous_is_stable_and_spreads(self):
        reps = [_StubReplica(i) for i in range(4)]
        router = self._router(reps)
        picks = {}
        for i in range(32):
            ak = _first_block_key(list(range(i, i + 20)))
            p1 = router._pick(set(), affinity_key=ak).idx
            p2 = router._pick(set(), affinity_key=ak).idx
            assert p1 == p2  # stable per key
            picks.setdefault(p1, 0)
            picks[p1] += 1
        assert len(picks) >= 2  # different keys spread across replicas

    def test_affinity_never_blocks_failover(self):
        prompt = list(range(20))
        key = _first_block_key(prompt)
        reps = [_StubReplica(0, keys=[key]), _StubReplica(1)]
        router = self._router(reps)
        # the advertising holder has been tried and died: excluded —
        # the pick MUST fall through to another live replica
        assert router._pick({0}, affinity_key=key).idx == 1
        # holder not ready (mid-respawn): same
        reps[0].ready = False
        assert router._pick(set(), affinity_key=key).idx == 1
        reps[1].ready = False
        assert router._pick(set(), affinity_key=key) is None

    def test_keyed_without_affinity_key_goes_least_loaded(self):
        reps = [_StubReplica(0, load=4), _StubReplica(1, load=1),
                _StubReplica(2, load=4)]
        router = self._router(reps)
        # keyed but no computable key: least-loaded, not round-robin
        for _ in range(3):
            assert router._pick(set(), keyed=True).idx == 1
        # load ties round-robin instead of pinning the lowest idx
        reps[0].load = reps[2].load = 1
        picked = {router._pick(set(), keyed=True).idx
                  for _ in range(6)}
        assert len(picked) == 3

    def test_unkeyed_and_short_prompts_skip_affinity(self):
        reps = [_StubReplica(0), _StubReplica(1)]
        router = self._router(reps)
        assert router._affinity_key({"prompt": list(range(20))}) is None
        # prompt shorter than one full shareable block
        assert router._affinity_key(
            {"prompt": [1, 2, 3], "key": "k"}) is None
        # no replica has advertised a page size yet
        for r in reps:
            r.page_size = None
        assert router._affinity_key(
            {"prompt": list(range(20)), "key": "k"}) is None
        assert router.affinity_routed_total == 0

    def test_end_to_end_steering_over_live_servers(self, model):
        """Two in-process servers behind a real router socket: the
        first keyed request lands somewhere and populates that
        replica's cache; once the advertisement is refreshed, later
        keyed requests with the same prefix steer to it."""
        prompts = _prompts(tails=(3, 5))
        srvs = [ServingServer(model, spill_bytes=1 << 20, **ENGINE_KW)
                for _ in range(2)]
        reps = []
        try:
            for i, s in enumerate(srvs):
                s.start()
                reps.append(_StubReplica(i, port=s.port))
            sup = _StubSup(reps)
            router = FailoverRouter(sup)
            port = router.start()
            try:
                p0 = [int(t) for t in prompts[0]]
                rep1 = client_request(
                    "127.0.0.1", port,
                    {"op": "generate", "prompt": p0,
                     "max_new_tokens": 4, "key": "a"}, timeout_s=120)
                assert "error" not in rep1, rep1
                # refresh advertisements the way the supervisor's
                # monitor does (stub sup has no monitor thread)
                for r, s in zip(reps, srvs):
                    h = client_request("127.0.0.1", s.port,
                                       {"op": "health"})
                    r.prefix_keys = frozenset(h["prefix_keys"])
                    r.page_size = h["page_size"]
                holder = [i for i, r in enumerate(reps)
                          if _first_block_key(p0) in r.prefix_keys]
                assert len(holder) == 1
                before = router.affinity_hits_total
                p1 = [int(t) for t in prompts[1]]  # same shared prefix
                rep2 = client_request(
                    "127.0.0.1", port,
                    {"op": "generate", "prompt": p1,
                     "max_new_tokens": 4, "key": "b"}, timeout_s=120)
                assert "error" not in rep2, rep2
                assert router.affinity_hits_total == before + 1
                # the steered replica actually reused the prefix
                st = client_request("127.0.0.1", srvs[holder[0]].port,
                                    {"op": "stats"})
                assert st["prefix_cache"]["hit_pages"] > 0
            finally:
                router.stop()
        finally:
            for s in srvs:
                s.stop()
