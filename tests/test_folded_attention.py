"""Folded (layout-native [B,S,E]) flash attention correctness in Pallas
interpreter mode — the single-K-block no-transpose path BERT shapes
route through (ops/pallas/folded_attention.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.nn_functional import scaled_dot_product_attention
from paddle_tpu.ops.pallas import flash_attention as fa
from paddle_tpu.ops.pallas import folded_attention as fo


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    orig = fo.pl.pallas_call
    monkeypatch.setattr(fo.pl, "pallas_call",
                        functools.partial(orig, interpret=True))
    yield


def _rand(b, s, h, d, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(
        rng.standard_normal((b, s, h, d)).astype(np.float32))
        for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("h,d", [(4, 64), (2, 128)])
def test_folded_forward_matches_reference(causal, h, d):
    q, k, v = _rand(2, 256, h, d)
    ref = scaled_dot_product_attention(q, k, v, is_causal=causal,
                                       use_flash=False)
    out = fo.folded_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_folded_backward_matches_reference(causal):
    q, k, v = _rand(1, 128, 2, 64, seed=3)

    def loss_folded(q_, k_, v_):
        return jnp.sum(fo.folded_attention(q_, k_, v_,
                                           causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(scaled_dot_product_attention(
            q_, k_, v_, is_causal=causal, use_flash=False) ** 2)

    g_fold = jax.grad(loss_folded, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_fold, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_folded_backward_odd_head_count():
    """h=6, d=64 -> 3 column groups of 2 heads: the lane grouping must
    not mix adjacent heads' gradients."""
    q, k, v = _rand(1, 128, 6, 64, seed=5)

    def loss_folded(q_, k_, v_):
        return jnp.sum(fo.folded_attention(q_, k_, v_) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(scaled_dot_product_attention(
            q_, k_, v_, use_flash=False) ** 2)

    g_fold = jax.grad(loss_folded, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_fold, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3)


def test_folded_bf16():
    q, k, v = (t.astype(jnp.bfloat16) for t in _rand(2, 128, 4, 64))
    out = fo.folded_attention(q, k, v)
    ref = scaled_dot_product_attention(q, k, v, use_flash=False)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32), rtol=3e-2, atol=3e-2)


def test_supported_gate():
    ok = fo.folded_attention_supported
    # BERT-base pretrain shape (needs a TPU-family backend or the AOT
    # force gate — exercise via the scoped context)
    with fa.force_flash_for_aot():
        assert ok((64, 512, 12, 64), (64, 512, 12, 64))
        assert ok((8, 512, 16, 64), (8, 512, 16, 64), causal=True)
        # cross-length, overlong, non-tiling head groups: all rejected
        assert not ok((1, 512, 12, 64), (1, 256, 12, 64))
        assert not ok((1, 2048, 12, 64), (1, 2048, 12, 64))
        assert not ok((1, 512, 1, 64), (1, 512, 1, 64))  # E=64 < 128
        assert not ok((1, 512, 3, 64), (1, 512, 3, 64))  # E=192
        # d=64 causal runs folded through the whole single-block
        # range (measured wins at 512 AND 1024); d=128 causal caps at
        # one 256-block (r6 calibrated cost model, FOLDED_CROSSOVER
        # .json: full-lane streaming's causal-pair skip wins from 512)
        assert ok((1, 1024, 8, 64), (1, 1024, 8, 64), causal=True)
        assert ok((1, 1024, 8, 64), (1, 1024, 8, 64), causal=False)
        assert not ok((1, 1024, 8, 128), (1, 1024, 8, 128),
                      causal=True)
        assert not ok((1, 512, 8, 128), (1, 512, 8, 128), causal=True)
        assert ok((1, 256, 8, 128), (1, 256, 8, 128), causal=True)
        # non-causal d=128 keeps the full single-block range
        assert ok((1, 512, 8, 128), (1, 512, 8, 128), causal=False)
    assert not ok((64, 512, 12, 64), (64, 512, 12, 64), backend="cpu")


def test_sdpa_routes_bert_shape_to_folded(monkeypatch):
    """scaled_dot_product_attention must take the folded kernel for
    single-block self-attention shapes (and stay off it for masked or
    dropout calls)."""
    import paddle_tpu.ops.nn_functional as NF

    taken = {}

    def fake_folded(q, k, v, causal=False, scale=None):
        taken["folded"] = True
        return q

    monkeypatch.setattr(NF, "_FLASH_MIN_SEQ", 512)
    import paddle_tpu.ops.pallas.folded_attention as fomod
    monkeypatch.setattr(fomod, "folded_attention", fake_folded)
    q = jnp.zeros((2, 512, 4, 64))
    with fa.force_flash_for_aot():
        out = NF.scaled_dot_product_attention(q, q, q)
        assert taken.get("folded") and out.shape == q.shape
        # an attn_mask must bypass the folded/flash path entirely
        taken.clear()
        mask = jnp.zeros((2, 1, 1, 512))
        NF.scaled_dot_product_attention(q, q, q, attn_mask=mask)
        assert "folded" not in taken


def test_folded_crossover_gate(monkeypatch):
    """The folded kernel engages from S>=256 (measured crossover: wins
    at 256, loses at 128 — no transposes, so lower than the streaming
    kernel's 512 gate), while sub-512 shapes must NOT fall through to
    the transposing flash path."""
    import paddle_tpu.ops.nn_functional as NF
    import paddle_tpu.ops.pallas.folded_attention as fomod
    import paddle_tpu.ops.pallas.flash_attention as famod

    taken = {}
    monkeypatch.setattr(fomod, "folded_attention",
                        lambda q, k, v, causal=False, scale=None:
                        taken.setdefault("folded", True) and q)
    monkeypatch.setattr(famod, "flash_attention",
                        lambda *a, **k:
                        (_ for _ in ()).throw(AssertionError(
                            "transposing flash taken below its gate")))
    with fa.force_flash_for_aot():
        q256 = jnp.zeros((2, 256, 4, 64))
        NF.scaled_dot_product_attention(q256, q256, q256)
        assert taken.get("folded"), "folded not engaged at S=256"
        # S=128: below the folded crossover -> plain XLA path
        taken.clear()
        q128 = jnp.zeros((2, 128, 4, 64))
        out = NF.scaled_dot_product_attention(q128, q128, q128)
        assert "folded" not in taken and out.shape == q128.shape
