"""Trainer/device-worker runtime over heavy-IO datasets.

Reference parity: framework/trainer.h MultiTrainer/DistMultiTrainer +
device_worker.h Hogwild/Downpour workers driven by
Executor.train_from_dataset (fluid/executor.py:1662), tested the way the
reference tests dataset trainers (test_dataset.py, test_monitor.py)."""

import os

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
from paddle_tpu import nn
from paddle_tpu.io.heavy_dataset import InMemoryDataset, QueueDataset
from paddle_tpu.jit import TrainStep


def _write_files(tmp_path, n_files=3, rows=40):
    files = []
    for fi in range(n_files):
        p = tmp_path / f"part-{fi}.txt"
        with open(p, "w") as f:
            for i in range(rows):
                sign = (i % 2) * 2 - 1
                f.write(f"feat:{sign}.0 1.0 2.0 3.0;label:{i % 2}\n")
        files.append(str(p))
    return files


def _make_step():
    pt.seed(0)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, feat, label):
            return nn.functional.cross_entropy(self.fc(feat),
                                               label.reshape(-1))

    m = M()
    return m, TrainStep(
        m, optim.SGD(learning_rate=0.2),
        lambda mm, b: mm(b["feat"].astype("float32"),
                         b["label"].astype("int32")))


def test_train_from_dataset_multitrainer(tmp_path):
    ds = InMemoryDataset()
    ds.set_filelist(_write_files(tmp_path))
    ds.set_batch_size(8)
    ds.set_thread(3)
    ds.load_into_memory()
    ds.local_shuffle()

    _, step = _make_step()
    exe = pt.static.Executor()
    first = exe.train_from_dataset(program=step, dataset=ds, thread=3)
    assert first["steps"] == 15  # 3 channels x ceil(40/8)
    second = exe.train_from_dataset(program=step, dataset=ds, thread=3)
    assert second["avg_loss"] < first["avg_loss"]


def test_train_from_dataset_queue(tmp_path):
    ds = QueueDataset()
    ds.set_filelist(_write_files(tmp_path, n_files=2, rows=16))
    ds.set_batch_size(4)
    ds.set_thread(2)

    _, step = _make_step()
    exe = pt.static.Executor()
    res = exe.train_from_dataset(program=step, dataset=ds, thread=2)
    assert res["steps"] == 8
    assert np.isfinite(res["avg_loss"])


def test_trainer_factory_and_worker_metrics(tmp_path):
    from paddle_tpu.framework import MultiTrainer, TrainerFactory

    tr = TrainerFactory.create("MultiTrainer", lambda b, w: 1.0,
                               thread_num=2)
    assert isinstance(tr, MultiTrainer)
    with pytest.raises(Exception):
        TrainerFactory.create("NopeTrainer", None)

    ds = InMemoryDataset()
    ds.set_filelist(_write_files(tmp_path, n_files=1, rows=8))
    ds.set_batch_size(4)
    ds.load_into_memory()
    res = tr.run(ds)
    assert res["steps"] == 2 and res["avg_loss"] == 1.0
    assert sum(int(w.metrics["steps"]) for w in tr.workers) == 2


def test_worker_error_propagates(tmp_path):
    from paddle_tpu.framework import MultiTrainer

    ds = InMemoryDataset()
    ds.set_filelist(_write_files(tmp_path, n_files=1, rows=4))
    ds.set_batch_size(2)
    ds.load_into_memory()

    def bad_step(batch, worker_id):
        raise RuntimeError("boom in worker")

    tr = MultiTrainer(bad_step, thread_num=2)
    with pytest.raises(RuntimeError, match="boom in worker"):
        tr.run(ds)


def test_dist_multitrainer_downpour_ps(tmp_path):
    """DownpourWorkers pull dense params from a live PSServer, step, and
    push grads back — end of run, the PS table moved (async-PS flow,
    reference device_worker.h:275)."""
    from paddle_tpu.distributed.ps import PSClient, PSServer
    from paddle_tpu.framework import DistMultiTrainer

    server = PSServer()
    model, step = _make_step()

    def get_flat():
        return np.concatenate(
            [np.asarray(v).ravel() for v in step.params.values()])

    shapes = {k: np.asarray(v).shape for k, v in step.params.items()}
    # lr=1.0: workers push param DELTAS, so the server-side SGD applies
    # them verbatim
    server.add_dense_table("dense_0", get_flat().shape, lr=1.0)
    server.start()
    try:
        client = PSClient([server.endpoint])
        client.push_dense_init("dense_0", get_flat())
        before = client.pull_dense("dense_0").copy()

        last = {"flat": get_flat()}

        def set_flat(vec):
            off = 0
            import jax.numpy as jnp
            new = {}
            for k, shp in shapes.items():
                n = int(np.prod(shp))
                new[k] = jnp.asarray(
                    vec[off:off + n].reshape(shp).astype(np.float32))
                off += n
            step.params = new
            last["flat"] = np.asarray(vec, np.float32)

        def get_grad():
            # server-side SGD: push the param DELTA as the gradient with
            # lr 1.0 semantics (delta = old - new)
            return last["flat"] - get_flat()

        ds = InMemoryDataset()
        ds.set_filelist(_write_files(tmp_path, n_files=1, rows=16))
        ds.set_batch_size(4)
        ds.load_into_memory()

        collate = pt.static.Executor._default_collate
        tr = DistMultiTrainer(
            lambda b, w: step(collate(b)), thread_num=2, ps_client=client,
            dense_table="dense_0", set_dense=set_flat,
            get_dense=get_flat, get_grad=get_grad)
        res = tr.run(ds)
        assert res["steps"] == 4
        after = client.pull_dense("dense_0")
        assert not np.allclose(before, after)
    finally:
        server.stop()


def test_channels_honor_drop_last(tmp_path):
    from paddle_tpu.framework import MultiTrainer

    ds = InMemoryDataset()
    ds.set_filelist(_write_files(tmp_path, n_files=1, rows=10))
    ds.set_batch_size(4)
    ds.drop_last = True
    ds.load_into_memory()
    tr = MultiTrainer(lambda b, w: float(len(b)), thread_num=1)
    res = tr.run(ds)
    assert res["steps"] == 2  # 10 rows -> 2 full batches, tail dropped
    ds.drop_last = False
    tr2 = MultiTrainer(lambda b, w: float(len(b)), thread_num=1)
    assert tr2.run(ds)["steps"] == 3


def test_program_dict_feed_by_name(tmp_path):
    """Dict batches bind to Program inputs BY NAME, not dict order."""
    from paddle_tpu.static import InputSpec, build_program

    ds = InMemoryDataset()
    ds.set_filelist(_write_files(tmp_path, n_files=1, rows=8))
    ds.set_batch_size(4)
    ds.load_into_memory()

    pt.seed(0)
    net = nn.Linear(4, 2)
    # declare inputs in the OPPOSITE order of the sample dict keys
    prog = build_program(
        lambda label, feat: nn.functional.cross_entropy(
            net(feat.astype("float32")),
            label.reshape(-1).astype("int32")),
        [InputSpec((None, 1), "int64", "label"),
         InputSpec((None, 4), "float32", "feat")])
    exe = pt.static.Executor()
    res = exe.infer_from_dataset(program=prog, dataset=ds, thread=1)
    assert res["steps"] == 2 and np.isfinite(res["avg_loss"])

    # and infer_from_dataset refuses a mutating TrainStep
    _, step = _make_step()
    with pytest.raises(Exception, match="must not mutate"):
        exe.infer_from_dataset(program=step, dataset=ds, thread=1)


def test_downpour_sparse_table_flow(tmp_path):
    """Embedding rows live SERVER-side (reference DownpourWorker sparse
    tables / heter-PS split): each cycle pulls the batch's rows into the
    local embedding, steps, and pushes row deltas back."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.ps import PSClient, PSServer
    from paddle_tpu.framework import DistMultiTrainer

    vocab, dim = 20, 4
    server = PSServer()
    server.add_sparse_table("emb", dim, lr=1.0)
    server.start()
    try:
        client = PSClient([server.endpoint])

        pt.seed(0)

        class CTR(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(vocab, dim)
                self.fc = nn.Linear(dim, 2)

            def forward(self, ids, label):
                h = self.emb(ids).mean(axis=1)
                return nn.functional.cross_entropy(self.fc(h), label)

        model = CTR()
        step = TrainStep(model, optim.SGD(learning_rate=0.3),
                         lambda m, b: m(b["ids"].astype("int32"),
                                        b["label"].astype("int32")))
        pulled = {}

        def sparse_pull(ps, batch):
            keys = np.unique(np.concatenate(
                [np.asarray(s["ids"]) for s in batch]).ravel())
            rows = ps.pull_sparse("emb", keys)
            w = np.array(step.params["emb.weight"])
            w[keys] = rows
            step.params = dict(step.params,
                               **{"emb.weight": jnp.asarray(w)})
            pulled["keys"], pulled["rows"] = keys, rows

        def sparse_push(ps, batch):
            keys = pulled["keys"]
            new_rows = np.asarray(step.params["emb.weight"])[keys]
            # server lr=1.0 applies the delta verbatim
            ps.push_sparse_grad("emb", keys, pulled["rows"] - new_rows)

        ds = InMemoryDataset()
        p = tmp_path / "ctr.txt"
        with open(p, "w") as f:
            for i in range(32):
                a, b = i % vocab, (i * 7 + 1) % vocab
                f.write(f"ids:{a} {b};label:{i % 2}\n")
        ds.set_filelist([str(p)])
        ds.set_batch_size(8)
        ds.load_into_memory()

        collate = pt.static.Executor._default_collate
        tr = DistMultiTrainer(
            lambda b, w: step(collate(b)), thread_num=2,
            ps_client=client, get_dense=None, set_dense=None,
            get_grad=None, sparse_pull=sparse_pull,
            sparse_push=sparse_push)
        res = tr.run(ds)
        assert res["steps"] == 4
        # the server table learned: rows for seen keys are nonzero
        rows = client.pull_sparse("emb", np.arange(vocab))
        assert np.abs(rows).sum() > 0
    finally:
        server.stop()
