"""Distribution API + utils tests.

Mirrors reference tests test_distribution.py (Uniform/Normal/Categorical)
and test_utils download/install_check behaviors under
python/paddle/fluid/tests/unittests/.
"""

import math
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distribution import Categorical, Normal, Uniform


def test_normal_sample_logprob_entropy_kl():
    d = Normal(1.0, 2.0)
    s = d.sample((20000,), seed=7)
    arr = np.asarray(s.value)
    assert abs(arr.mean() - 1.0) < 0.1
    assert abs(arr.std() - 2.0) < 0.1

    lp = float(np.asarray(d.log_prob(
        pt.to_tensor(np.float32(1.0))).value).squeeze())
    expect = -math.log(2.0) - 0.5 * math.log(2 * math.pi)
    assert abs(lp - expect) < 1e-5

    ent = float(np.asarray(d.entropy().value).squeeze())
    assert abs(ent - (0.5 + 0.5 * math.log(2 * math.pi)
                      + math.log(2.0))) < 1e-5

    other = Normal(0.0, 1.0)
    kl = float(np.asarray(d.kl_divergence(other).value).squeeze())
    # KL(N(1,4)||N(0,1)) = 0.5*(4 + 1 - 1 - ln 4)
    assert abs(kl - 0.5 * (4 + 1 - 1 - math.log(4))) < 1e-5
    assert abs(float(np.asarray(d.kl_divergence(d).value).squeeze())) < 1e-6


def test_uniform():
    d = Uniform(-1.0, 3.0)
    s = np.asarray(d.sample((10000,), seed=3).value)
    assert s.min() >= -1.0 and s.max() < 3.0
    assert abs(s.mean() - 1.0) < 0.1
    assert abs(float(np.asarray(d.entropy().value).squeeze())
               - math.log(4.0)) < 1e-6
    lp_in = float(np.asarray(d.log_prob(
        pt.to_tensor(np.float32(0.0))).value).squeeze())
    assert abs(lp_in + math.log(4.0)) < 1e-6
    assert float(np.asarray(d.log_prob(
        pt.to_tensor(np.float32(5.0))).value).squeeze()) == -np.inf


def test_categorical():
    logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
    d = Categorical(logits)
    probs = np.asarray(d.probs().value)
    np.testing.assert_allclose(probs, [0.2, 0.3, 0.5], rtol=1e-5)
    s = np.asarray(d.sample((20000,), seed=5).value)
    freq = np.bincount(s, minlength=3) / s.size
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
    ent = float(d.entropy().value)
    assert abs(ent - (-(0.2 * math.log(0.2) + 0.3 * math.log(0.3)
                        + 0.5 * math.log(0.5)))) < 1e-5
    other = Categorical(np.zeros(3, np.float32))
    kl = float(d.kl_divergence(other).value)
    expect = sum(p * math.log(p / (1 / 3))
                 for p in [0.2, 0.3, 0.5])
    assert abs(kl - expect) < 1e-5
    lp = np.asarray(d.log_prob(pt.to_tensor(
        np.array([0, 2], np.int64))).value)
    np.testing.assert_allclose(lp, np.log([0.2, 0.5]), rtol=1e-5)


def test_download_cache_and_file_url(tmp_path):
    from paddle_tpu.utils.download import get_path_from_url, is_url
    src = tmp_path / "weights.bin"
    src.write_bytes(b"abc123" * 100)
    assert is_url("file:///x") and is_url("https://x") and not is_url("/x")
    got = get_path_from_url(f"file://{src}", root_dir=str(tmp_path / "cache"))
    assert os.path.exists(got)
    assert open(got, "rb").read() == b"abc123" * 100
    # cache hit: delete source, fetch again
    src.unlink()
    got2 = get_path_from_url(f"file://{src}",
                             root_dir=str(tmp_path / "cache"))
    assert got2 == got
    import hashlib
    md5 = hashlib.md5(b"abc123" * 100).hexdigest()
    got3 = get_path_from_url(f"file://{src}",
                             root_dir=str(tmp_path / "cache"), md5sum=md5)
    assert got3 == got


def test_download_archive_decompress(tmp_path):
    import tarfile
    from paddle_tpu.utils.download import get_path_from_url
    d = tmp_path / "model"
    d.mkdir()
    (d / "w.txt").write_text("hi")
    tar = tmp_path / "model.tar"
    with tarfile.open(tar, "w") as tf:
        tf.add(d, arcname="model")
    out = get_path_from_url(f"file://{tar}",
                            root_dir=str(tmp_path / "cache2"))
    assert os.path.isdir(out)
    assert open(os.path.join(out, "w.txt")).read() == "hi"


def test_run_check():
    from paddle_tpu.utils import run_check
    run_check()


def test_distribution_arg_validation_and_promotion():
    """reference python/paddle/distribution.py:70-136 _validate_args /
    _to_tensor / _check_values_dtype_in_probs semantics."""
    import warnings as _w

    import jax.numpy as jnp
    import pytest as _pt

    from paddle_tpu.distribution import Normal, Uniform

    # mixing Tensor and python-number args is rejected
    with _pt.raises(ValueError):
        Normal(pt.to_tensor([0.0]), 1.0)
    with _pt.raises(ValueError):
        Uniform(0.0, pt.to_tensor([1.0]))

    # unsupported arg types are a TypeError
    with _pt.raises(TypeError):
        Normal("zero", "one")

    # floats become shape-[1] params, mutually broadcast with lists
    n = Normal(0.0, [1.0, 2.0])
    assert n.loc.shape == (2,) and n.scale.shape == (2,)

    # int lists warn and promote to float32
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        u = Uniform([0, 0], [2, 4])
    assert u.low.dtype == jnp.float32
    assert any("float32" in str(r.message) for r in rec)

    # float64 args keep float64 (promotion over the pair)
    f64 = np.array([0.0, 1.0], np.float64)
    n64 = Normal(f64, np.array([1.0], np.float64))
    assert n64.loc.dtype == jnp.float64 or n64.loc.dtype == jnp.float32
    # (jax may downcast without x64 mode; shape promotion still applies)
    assert n64.loc.shape == (2,)

    # value dtype converts (with a warning) to the param dtype
    n32 = Normal([0.0], [1.0])
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        lp = n32.log_prob(jnp.asarray([0.5], jnp.bfloat16))
    assert np.asarray(lp).dtype == np.float32
    assert any("converted" in str(r.message) for r in rec)

    # integer values in log_prob are rejected (floating only)
    with _pt.raises(TypeError):
        n32.log_prob(np.array([1], np.int32))

    # samples follow the parameter dtype
    s = n32.sample([3])
    assert np.asarray(s).dtype == np.float32 and tuple(s.shape) == (3, 1)
