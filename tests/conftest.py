"""Test configuration: run everything on a virtual 8-device CPU mesh.

Distributed/sharding tests validate multi-chip semantics on fake CPU
devices (the driver's dryrun_multichip does the same); bench.py runs on the
real TPU chip with the default environment.
"""

import os

# Compile-only TPU topologies (scale-proof / longseq AOT tests) must not
# probe the GCP metadata server: off-cloud, libtpu retries those fetches
# for ~8 minutes before giving up, stalling the whole fast lane.
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")

# Must be set before the first backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " +
                               _flag).strip()

import jax  # noqa: E402

# The container's sitecustomize registers the axon TPU plugin and pins
# JAX_PLATFORMS=axon at interpreter start; tests must run on host CPU.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np
    return np.random.default_rng(0)
