"""Test configuration: run everything on a virtual 8-device CPU mesh.

Distributed/sharding tests validate multi-chip semantics on fake CPU
devices (the driver's dryrun_multichip does the same); bench.py runs on the
real TPU chip with the default environment.
"""

import os

# Compile-only TPU topologies (scale-proof / longseq AOT tests) must not
# probe the GCP metadata server: off-cloud, libtpu retries those fetches
# for ~8 minutes before giving up, stalling the whole fast lane.
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")

# Must be set before the first backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " +
                               _flag).strip()

import jax  # noqa: E402

# The container's sitecustomize registers the axon TPU plugin and pins
# JAX_PLATFORMS=axon at interpreter start; tests must run on host CPU.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np
    return np.random.default_rng(0)


@pytest.fixture(scope="module")
def module_compile_cache(tmp_path_factory):
    """Module-scoped persistent compile cache (core/compile_cache.py)
    for engine-heavy test files: their tests build fresh engines over
    the same gpt_tiny program shapes, so without a cache each file
    pays the same XLA compiles dozens of times — most of its tier-1
    wall cost. Module scope means one fresh temp-dir cache per
    requesting file (pytest caches per-module), hermetic and fully
    detached on teardown. OPT-IN via a module-level autouse fixture —
    never autouse here: compile-cache unit tests assert the disabled
    default, and cheap files don't need the toggle."""
    from paddle_tpu.core.compile_cache import (disable_compile_cache,
                                               enable_compile_cache)
    old = os.environ.get("PADDLE_TPU_COMPILE_CACHE")
    path = str(tmp_path_factory.mktemp("module_compile_cache"))
    os.environ["PADDLE_TPU_COMPILE_CACHE"] = path
    enable_compile_cache(path)
    yield path
    disable_compile_cache()
    if old is None:
        os.environ.pop("PADDLE_TPU_COMPILE_CACHE", None)
    else:
        os.environ["PADDLE_TPU_COMPILE_CACHE"] = old


@pytest.fixture
def cpu_mesh_json():
    """Run a mesh payload in a FRESH subprocess pinned to an N-device
    CPU host platform (core/cpu_mesh.py): the child prints its result
    via ``emit_result``; the fixture returns the parsed object. For
    mesh tests that must not share jax state with this process — the
    in-process suite is already 8 fake devices (see module top), but a
    cold subprocess also pins that the XLA_FLAGS plumbing itself works
    outside the conftest's environment (bench_all, production CLIs)."""
    from paddle_tpu.core.cpu_mesh import run_cpu_mesh_json
    return run_cpu_mesh_json
