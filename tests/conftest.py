"""Test configuration: run everything on a virtual 8-device CPU mesh.

Distributed/sharding tests validate multi-chip semantics on fake CPU
devices (the driver's dryrun_multichip does the same); bench.py runs on the
real TPU chip with the default environment.
"""

import os

# Compile-only TPU topologies (scale-proof / longseq AOT tests) must not
# probe the GCP metadata server: off-cloud, libtpu retries those fetches
# for ~8 minutes before giving up, stalling the whole fast lane.
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")

# Must be set before the first backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " +
                               _flag).strip()

import jax  # noqa: E402

# The container's sitecustomize registers the axon TPU plugin and pins
# JAX_PLATFORMS=axon at interpreter start; tests must run on host CPU.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Stray serving-process guard (r13). A paddle_tpu.serving server leaked
# from a PRIOR run (the PR 7 tier-1 hazard: one sat in its poll loop
# and pushed a timed suite past the 870s cap) competes with the timed
# lane for CPU. At session start we scan for serving/supervisor/chaos
# processes that do not belong to this session's process tree:
# detection-only by default (a developer may legitimately run a server
# next to the suite — never kill what we didn't start), and even under
# CI (env CI set) the kill is scoped to ORPHANED matches — processes
# reparented to init, the signature of a survivor whose spawning run
# died. A live concurrent run's server still has its supervisor/pytest
# as parent and is reported but spared, so two jobs sharing a runner
# cannot fratricide each other. Known limit: a concurrent job that
# INTENTIONALLY daemonizes its server (setsid/double-fork reparents it
# to init while the job still uses it) looks exactly like a leak — on
# shared bare-metal runners such jobs should not rely on surviving
# another job's CI-mode session start, or CI should be unset there.
# ---------------------------------------------------------------------------

_SERVING_MARKERS = ("paddle_tpu.serving.server",
                    "paddle_tpu.serving.supervisor",
                    "tools/chaos_serving.py", "chaos_serving.py")


def _proc_ancestors():
    """PIDs of this process and its ancestors (never guard-kill the
    runner's own tree — e.g. a supervisor driving pytest)."""
    pids = set()
    pid = os.getpid()
    for _ in range(64):
        if pid <= 0 or pid in pids:
            break
        pids.add(pid)
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().split(")")[-1].split()[1])  # ppid
        except (OSError, ValueError, IndexError):
            break
    return pids


def _stray_serving_procs():
    """[(pid, ppid, cmdline)] of serving-marker processes outside this
    session's ancestry. /proc scan (Linux — the CI/test platform);
    empty elsewhere rather than guessing."""
    own = _proc_ancestors()
    found = []
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return found
    for pid in pids:
        if pid in own:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            continue  # raced with exit, or not ours to read
        if any(m in cmd for m in _SERVING_MARKERS):
            found.append((pid, ppid, cmd))
    return found


def _adopted_by_live_supervisor(pid: int) -> bool:
    """Autoscaler-managed replicas (r21) carry PT_SUPERVISOR_JOURNAL
    in their environment. An orphaned (ppid==1) replica is NOT a leak
    when the journal it points at names a LIVE supervisor_pid: its
    original parent died, but a restarted supervisor ADOPTED it from
    the journal — killing it would scale down someone's live fleet.
    Any read/parse failure returns False (the pre-r21 kill rule)."""
    import json
    try:
        with open(f"/proc/{pid}/environ", "rb") as f:
            raw = f.read()
        env = dict(p.split(b"=", 1) for p in raw.split(b"\0")
                   if b"=" in p)
        journal = env.get(b"PT_SUPERVISOR_JOURNAL")
        if not journal:
            return False
        with open(journal.decode("utf-8", "replace"),
                  encoding="utf-8") as f:
            body = (json.load(f) or {}).get("body") or {}
        sup_pid = body.get("supervisor_pid")
        return isinstance(sup_pid, int) \
            and os.path.isdir(f"/proc/{sup_pid}")
    except (OSError, ValueError, AttributeError):
        return False


def _handle_stray_serving(kill: bool):
    """Detect stray serving processes; with ``kill=True`` reap the
    ORPHANED ones (ppid == 1: their spawning run is dead — a process
    with a live parent belongs to someone and is only reported).
    Autoscaler-adopted replicas (orphaned by pid but owned by a live
    supervisor through the fleet journal, r21) are spared. Returns
    ``[(pid, ppid, cmdline, killed)]``. Split from the hook so the
    guard's detection-only and orphans-only contracts are directly
    testable."""
    import signal
    out = []
    for pid, ppid, cmd in _stray_serving_procs():
        killed = False
        if kill and ppid == 1 and not _adopted_by_live_supervisor(pid):
            try:
                os.kill(pid, signal.SIGKILL)
                killed = True
            except OSError:
                pass
        out.append((pid, ppid, cmd, killed))
    return out


def pytest_sessionstart(session):
    kill = bool(os.environ.get("CI"))
    for pid, ppid, cmd, killed in _handle_stray_serving(kill=kill):
        if killed:
            action = "killed (CI, orphaned)"
        elif kill and ppid == 1:
            action = "NOT killed (adopted by a live supervisor via " \
                     "its fleet journal)"
        elif kill:
            action = f"NOT killed (live parent {ppid} — belongs to a " \
                     f"concurrent run)"
        else:
            action = "NOT killed (detection-only outside CI; kill it " \
                     "before timed runs)"
        print(f"[conftest] stray serving process pid {pid}: "
              f"{cmd[:120]} — {action}", flush=True)


@pytest.fixture
def rng():
    import numpy as np
    return np.random.default_rng(0)


@pytest.fixture(scope="module")
def module_compile_cache(tmp_path_factory):
    """Module-scoped persistent compile cache (core/compile_cache.py)
    for engine-heavy test files: their tests build fresh engines over
    the same gpt_tiny program shapes, so without a cache each file
    pays the same XLA compiles dozens of times — most of its tier-1
    wall cost. Module scope means one fresh temp-dir cache per
    requesting file (pytest caches per-module), hermetic and fully
    detached on teardown. OPT-IN via a module-level autouse fixture —
    never autouse here: compile-cache unit tests assert the disabled
    default, and cheap files don't need the toggle."""
    from paddle_tpu.core.compile_cache import (disable_compile_cache,
                                               enable_compile_cache)
    old = os.environ.get("PADDLE_TPU_COMPILE_CACHE")
    path = str(tmp_path_factory.mktemp("module_compile_cache"))
    os.environ["PADDLE_TPU_COMPILE_CACHE"] = path
    enable_compile_cache(path)
    yield path
    disable_compile_cache()
    if old is None:
        os.environ.pop("PADDLE_TPU_COMPILE_CACHE", None)
    else:
        os.environ["PADDLE_TPU_COMPILE_CACHE"] = old


@pytest.fixture
def cpu_mesh_json():
    """Run a mesh payload in a FRESH subprocess pinned to an N-device
    CPU host platform (core/cpu_mesh.py): the child prints its result
    via ``emit_result``; the fixture returns the parsed object. For
    mesh tests that must not share jax state with this process — the
    in-process suite is already 8 fake devices (see module top), but a
    cold subprocess also pins that the XLA_FLAGS plumbing itself works
    outside the conftest's environment (bench_all, production CLIs)."""
    from paddle_tpu.core.cpu_mesh import run_cpu_mesh_json
    return run_cpu_mesh_json
