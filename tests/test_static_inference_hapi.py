"""Static program path, inference predictor, hapi Model, metrics.

Mirrors reference tests: test_static_save_load, inference api tests,
test_model.py (hapi), test_metrics.py.
"""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as optim
from paddle_tpu.static import (Executor, InputSpec, build_program,
                               load_inference_model, save_inference_model)


def test_build_program_and_run():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    prog = build_program(net, [InputSpec((-1, 4), "float32", "x")])
    x = np.random.default_rng(0).standard_normal((3, 4)).astype(np.float32)
    out = prog.run(x)
    net.eval()
    np.testing.assert_allclose(np.asarray(out), net(pt.to_tensor(x)).numpy(),
                               rtol=1e-5)
    # lowered program text is inspectable (ProgramDesc analog)
    assert "stablehlo" in prog.lowered_text() or "func" in \
        prog.lowered_text()


def test_executor_feed_fetch():
    net = nn.Linear(4, 2)
    prog = build_program(net, [InputSpec((-1, 4), "float32", "x")])
    exe = Executor()
    x = np.ones((2, 4), np.float32)
    outs = exe.run(prog, feed={"x": x}, fetch_list=None)
    assert outs[0].shape == (2, 2)


def test_save_load_inference_model_roundtrip():
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    x = np.random.default_rng(1).standard_normal((5, 4)).astype(np.float32)
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        save_inference_model(prefix, [InputSpec((5, 4), "float32", "x")],
                             layer=net)
        loaded = load_inference_model(prefix)
        net.eval()
        np.testing.assert_allclose(np.asarray(loaded.run(x)),
                                   net(pt.to_tensor(x)).numpy(), rtol=1e-5)


def test_predictor_api():
    from paddle_tpu.inference import Config, create_predictor

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    x = np.random.default_rng(2).standard_normal((2, 4)).astype(np.float32)
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "serve")
        save_inference_model(prefix, [InputSpec((2, 4), "float32", "x")],
                             layer=net)
        cfg = Config(prefix)
        pred = create_predictor(cfg)
        assert pred.get_input_names() == ["x"]
        h = pred.get_input_handle("x")
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        got = out.copy_to_cpu()
        net.eval()
        np.testing.assert_allclose(got, net(pt.to_tensor(x)).numpy(),
                                   rtol=1e-5)


def test_to_static_decorator():
    from paddle_tpu.jit import to_static

    calls = {"n": 0}

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            calls["n"] += 1
            return self.fc(x)

    net = Net()
    eager_out = net(pt.randn((2, 4)))
    net2 = to_static(net)
    x = pt.randn((2, 4))
    o1 = net2(x)
    o2 = net2(x)
    assert o1.shape == (2, 2)
    np.testing.assert_allclose(o1.numpy(), o2.numpy())


def test_hapi_model_fit_evaluate_predict():
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.metric import Accuracy

    rng = np.random.default_rng(0)
    X = rng.standard_normal((128, 8)).astype(np.float32)
    W = rng.standard_normal((8, 3)).astype(np.float32)
    y = (X @ W).argmax(-1).astype(np.int64)
    ds = TensorDataset([X, y])

    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 3))
    model = Model(net)
    model.prepare(optimizer=optim.Adam(learning_rate=0.01),
                  loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    model.fit(ds, epochs=8, batch_size=32, verbose=0)
    logs = model.evaluate(ds, batch_size=32, verbose=0)
    assert logs["acc"] > 0.7, logs
    preds = model.predict(ds, batch_size=32, stack_outputs=True)
    assert preds[0].shape == (128, 3)


def test_hapi_early_stopping_and_checkpoint():
    from paddle_tpu.hapi import EarlyStopping, Model
    from paddle_tpu.io import TensorDataset

    X = np.random.default_rng(3).standard_normal((32, 4)).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    ds = TensorDataset([X, y])
    net = nn.Linear(4, 1)
    model = Model(net)
    model.prepare(optimizer=optim.SGD(learning_rate=0.0),
                  loss=nn.MSELoss())
    es = EarlyStopping(monitor="loss", patience=1, verbose=0)
    with tempfile.TemporaryDirectory() as d:
        model.fit(ds, eval_data=ds, epochs=10, batch_size=16, verbose=0,
                  callbacks=[es], save_dir=d)
        assert model.stop_training
        assert os.path.exists(os.path.join(d, "final.pdparams"))


def test_model_save_load_roundtrip():
    from paddle_tpu.hapi import Model
    net = nn.Linear(4, 2)
    model = Model(net)
    model.prepare(optimizer=optim.Adam(learning_rate=0.01,
                                       parameters=net.parameters()),
                  loss=nn.MSELoss())
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "ckpt")
        model.save(p)
        net2 = nn.Linear(4, 2)
        model2 = Model(net2)
        model2.prepare(optimizer=optim.Adam(
            learning_rate=0.01, parameters=net2.parameters()),
            loss=nn.MSELoss())
        model2.load(p)
        x = pt.randn((2, 4))
        net.eval()
        net2.eval()
        np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(),
                                   rtol=1e-6)


def test_metrics():
    from paddle_tpu.metric import Accuracy, Auc, Precision, Recall

    acc = Accuracy()
    pred = pt.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    label = pt.to_tensor(np.array([1, 0]))
    correct = acc.compute(pred, label)
    acc.update(correct)
    assert acc.accumulate() == 1.0

    p = Precision()
    p.update(np.array([0.9, 0.8, 0.1]), np.array([1, 0, 1]))
    assert abs(p.accumulate() - 0.5) < 1e-6

    r = Recall()
    r.update(np.array([0.9, 0.8, 0.1]), np.array([1, 0, 1]))
    assert abs(r.accumulate() - 0.5) < 1e-6

    auc = Auc()
    auc.update(np.array([0.9, 0.8, 0.3, 0.1]), np.array([1, 1, 0, 0]))
    assert auc.accumulate() == 1.0


def test_functional_accuracy():
    from paddle_tpu.metric import accuracy
    pred = pt.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    label = pt.to_tensor(np.array([[1], [1]]))
    a = accuracy(pred, label, k=1)
    assert abs(float(a.numpy()) - 0.5) < 1e-6


def test_visualdl_callback_scalars(tmp_path):
    """VisualDL-style scalar logging (reference: hapi/callbacks.py:839):
    per-batch train scalars and per-epoch scalars stream to the logdir
    and load back in order."""
    import paddle_tpu.optimizer as optim
    from paddle_tpu.hapi import Model, VisualDL

    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model = Model(net)
    model.prepare(
        optim.SGD(learning_rate=0.1, parameters=net.parameters()),
        nn.CrossEntropyLoss())
    x = np.random.default_rng(0).normal(size=(32, 4)).astype("float32")
    y = np.random.default_rng(1).integers(0, 2, 32).astype("int64")
    logdir = str(tmp_path / "vdl")
    model.fit(list(zip(x.reshape(8, 4, 4), y.reshape(8, 4))), epochs=2,
              callbacks=[VisualDL(log_dir=logdir)], verbose=0)
    scalars = VisualDL.read_scalars(logdir, "train")
    assert "train/loss" in scalars
    steps = [s for s, _ in scalars["train/loss"]]
    assert len(steps) == 16 and steps == sorted(steps)
    assert "train-epoch/loss" in VisualDL.read_scalars(logdir,
                                                       "train-epoch")
