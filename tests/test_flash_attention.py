"""Flash-attention kernel correctness in Pallas interpreter mode (CPU) —
the same ref-vs-optimized contract the reference uses for its JIT kernels
(paddle/fluid/operators/jit: refer/ scalar versions vs gen/ optimized)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.nn_functional import scaled_dot_product_attention
from paddle_tpu.ops.pallas import flash_attention as fa


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # run the Mosaic kernels via the Pallas interpreter on CPU
    orig = fa.pl.pallas_call
    monkeypatch.setattr(fa.pl, "pallas_call",
                        functools.partial(orig, interpret=True))
    yield


def _rand(b, s, h, d, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((b, s, h, d)).astype(np.float32),
            rng.standard_normal((b, s, h, d)).astype(np.float32),
            rng.standard_normal((b, s, h, d)).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    q, k, v = _rand(1, 256, 2, 64)
    ref = scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), is_causal=causal,
                                       use_flash=False)
    out = fa.flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    q, k, v = _rand(1, 128, 1, 64, seed=1)

    def loss_flash(q_, k_, v_):
        return jnp.sum(fa.flash_attention(q_, k_, v_, causal=causal) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(scaled_dot_product_attention(
            q_, k_, v_, is_causal=causal, use_flash=False) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_supported_gate():
    assert not fa.flash_attention_supported((1, 100, 2, 64), (1, 100, 2, 64),
                                            backend="tpu")
    assert fa.flash_attention_supported((1, 256, 2, 64), (1, 256, 2, 64),
                                        backend="tpu")
    assert not fa.flash_attention_supported((1, 256, 2, 64), (1, 256, 2, 64),
                                            backend="cpu")


def test_resolve_blocks_divisor_fallback():
    """S=640 (multiple of 128, not of 512) must stay on the flash path."""
    from paddle_tpu.ops.pallas.flash_attention import (
        _resolve_blocks, flash_attention_supported)

    assert _resolve_blocks(640, 640, 512, 512) == (128, 128)
    assert _resolve_blocks(1024, 1024, 512, 512) == (512, 512)
    assert _resolve_blocks(256, 1024, 512, 512) == (256, 512)
    assert flash_attention_supported((2, 640, 4, 64), (2, 640, 4, 64),
                                     backend="tpu")
    assert not flash_attention_supported((2, 100, 4, 64), (2, 100, 4, 64),
                                         backend="tpu")


def test_flash_bf16_matches_f32_reference():
    """bf16 operands (MXU full-rate path): forward + grads must stay
    within bf16 tolerance of the f32 reference — guards the
    preferred_element_type=f32 accumulation contract."""
    q, k, v = _rand(1, 256, 2, 64, seed=5)
    qb, kb, vb = (jnp.asarray(a, jnp.bfloat16) for a in (q, k, v))

    out_b = fa.flash_attention(qb, kb, vb, causal=True)
    out_f = scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        is_causal=True, use_flash=False)
    np.testing.assert_allclose(np.asarray(out_b, np.float32),
                               np.asarray(out_f), atol=2e-2, rtol=2e-2)

    def loss_b(q_, k_, v_):
        return fa.flash_attention(q_, k_, v_, causal=True).astype(
            jnp.float32).sum()

    def loss_f(q_, k_, v_):
        return scaled_dot_product_attention(
            q_, k_, v_, is_causal=True, use_flash=False).sum()

    gb = jax.grad(loss_b, argnums=(0, 1, 2))(qb, kb, vb)
    gf = jax.grad(loss_f, argnums=(0, 1, 2))(jnp.asarray(q),
                                             jnp.asarray(k),
                                             jnp.asarray(v))
    for got, exp, name in zip(gb, gf, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(exp),
            atol=0.25, rtol=0.08,
            err_msg=f"d{name} diverged beyond bf16 tolerance")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_multiblock_matches_reference(causal):
    """The streaming path with REAL multi-block grids (nq=nk=4): scratch
    init/carry/finish, cross-block causal skip, and the clamped masked-
    step index maps all execute (single-block shapes collapse them)."""
    q, k, v = _rand(2, 512, 2, 64, seed=3)

    def loss_flash(q_, k_, v_):
        return jnp.sum(fa.flash_attention(q_, k_, v_, causal=causal,
                                          block_q=128, block_k=128) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(scaled_dot_product_attention(
            q_, k_, v_, is_causal=causal, use_flash=False) ** 2)

    out = fa.flash_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), causal=causal,
                             block_q=128, block_k=128)
    ref = scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), is_causal=causal,
                                       use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_lse_block_merge_matches_dense():
    """flash_attention_lse + merge_attention_blocks over a K/V split
    equals one dense attention — the ring-attention hop contract —
    including gradients THROUGH the differentiable lse. (The causal
    schedule is covered by test_ring_flash_matches_dense.)"""
    from paddle_tpu.distributed.sp import merge_attention_blocks

    b, s, h, d = 1, 512, 2, 64
    q, k, v = _rand(b, s, h, d, seed=7)
    qj, kj, vj = map(jnp.asarray, (q, k, v))
    nblk = 4
    blk = s // nblk

    def merged(q_, k_, v_):
        acc = jnp.zeros(q_.shape, jnp.float32)
        lse = jnp.full((b, s, h), -jnp.inf, jnp.float32)
        for i in range(nblk):
            kb = k_[:, i * blk:(i + 1) * blk]
            vb = v_[:, i * blk:(i + 1) * blk]
            ob, lb = fa.flash_attention_lse(q_, kb, vb, causal=False)
            acc, lse = merge_attention_blocks(acc, lse, ob, lb)
        return acc.astype(q_.dtype)

    out = merged(qj, kj, vj)
    ref = scaled_dot_product_attention(qj, kj, vj, is_causal=False,
                                       use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    g_m = jax.grad(lambda a, b_, c: jnp.sum(merged(a, b_, c) ** 2),
                   argnums=(0, 1, 2))(qj, kj, vj)
    g_r = jax.grad(lambda a, b_, c: jnp.sum(scaled_dot_product_attention(
        a, b_, c, is_causal=False, use_flash=False) ** 2),
        argnums=(0, 1, 2))(qj, kj, vj)
    for gm, gr, name in zip(g_m, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(gm), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(causal):
    """Ring attention with the flash hop (use_flash=True) over a 4-way
    sequence shard matches dense attention, fwd and grads."""
    from paddle_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.sp import ring_attention

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(jax.devices()[:4]), ("sep",))
    b, s, h, d = 1, 512, 2, 64
    q, k, v = _rand(b, s, h, d, seed=9)
    qj, kj, vj = map(jnp.asarray, (q, k, v))
    spec = P(None, "sep")

    def ring(q_, k_, v_):
        return shard_map(
            lambda a, b_, c: ring_attention(a, b_, c, causal=causal,
                                            use_flash=True),
            mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False)(q_, k_, v_)

    out = ring(qj, kj, vj)
    ref = scaled_dot_product_attention(qj, kj, vj, is_causal=causal,
                                       use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    g_m = jax.grad(lambda a, b_, c: jnp.sum(ring(a, b_, c) ** 2),
                   argnums=(0, 1, 2))(qj, kj, vj)
    g_r = jax.grad(lambda a, b_, c: jnp.sum(scaled_dot_product_attention(
        a, b_, c, is_causal=causal, use_flash=False) ** 2),
        argnums=(0, 1, 2))(qj, kj, vj)
    for gm, gr, name in zip(g_m, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(gm), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_zigzag_ring_flash_matches_dense():
    """Balanced zigzag causal ring on the flash hop: fwd + grads match
    dense attention after the layout permutation."""
    from paddle_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.distributed.sp import ring_attention, zigzag_permutation

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(jax.devices()[:4]), ("sep",))
    b, s, h, d = 1, 1024, 2, 64
    q, k, v = _rand(b, s, h, d, seed=11)
    perm, inv = zigzag_permutation(s, 4)
    qj, kj, vj = (jnp.asarray(q[:, perm]), jnp.asarray(k[:, perm]),
                  jnp.asarray(v[:, perm]))
    spec = P(None, "sep")

    def ring(q_, k_, v_):
        return shard_map(
            lambda a, b_, c: ring_attention(a, b_, c, causal=True,
                                            use_flash=True,
                                            layout="zigzag"),
            mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False)(q_, k_, v_)

    out = ring(qj, kj, vj)
    ref = scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), is_causal=True,
        use_flash=False)
    np.testing.assert_allclose(np.asarray(out)[:, inv], np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    g_m = jax.grad(lambda a, b_, c: jnp.sum(ring(a, b_, c) ** 2),
                   argnums=(0, 1, 2))(qj, kj, vj)
    g_r = jax.grad(lambda a, b_, c: jnp.sum(scaled_dot_product_attention(
        a, b_, c, is_causal=True, use_flash=False) ** 2),
        argnums=(0, 1, 2))(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for gm, gr, name in zip(g_m, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(gm)[:, inv],
                                   np.asarray(gr), rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_sdpa_flash_autoselect_heuristic(monkeypatch):
    """use_flash tri-state: None = auto (flash only at long key
    lengths), True = force, False = never. Regression: the GPT config
    flag was silently ignored on the main path before r4."""
    import paddle_tpu.ops.pallas.flash_attention as fa
    from paddle_tpu.ops import nn_functional as NF

    calls = []
    monkeypatch.setattr(fa, "flash_attention_supported",
                        lambda *a, **k: True)
    monkeypatch.setattr(
        fa, "flash_attention",
        lambda q, k, v, causal=False, scale=None: calls.append(1) or q)

    q = jnp.zeros((1, 256, 2, 64))
    NF.scaled_dot_product_attention(q, q, q)  # auto, short: XLA path
    assert not calls
    NF.scaled_dot_product_attention(q, q, q, use_flash=True)  # forced
    assert len(calls) == 1
    # measured r4 crossover: flash wins from S=512 up (BERT-base body
    # 243 -> 216.6 ms/step), XLA wins at S<=256
    mid_q = jnp.zeros((1, 512, 2, 64))
    NF.scaled_dot_product_attention(mid_q, mid_q, mid_q)  # auto, >=512
    assert len(calls) == 2
    long_q = jnp.zeros((1, 4096, 2, 64))
    NF.scaled_dot_product_attention(long_q, long_q, long_q)  # auto, long
    assert len(calls) == 3
    NF.scaled_dot_product_attention(long_q, long_q, long_q,
                                    use_flash=False)
    assert len(calls) == 3


def test_gpt_flash_flag_plumbs_to_attention(monkeypatch):
    """GPTConfig(use_flash_attention=False) must actually bypass the
    flash kernel even where the auto heuristic would pick it."""
    import paddle_tpu as pt
    import paddle_tpu.ops.pallas.flash_attention as fa
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    monkeypatch.setattr(fa, "flash_attention_supported",
                        lambda *a, **k: True)

    def boom(*a, **k):
        raise AssertionError("flash kernel reached with flag off")

    monkeypatch.setattr(fa, "flash_attention", boom)
    monkeypatch.setenv("PT_FLASH_MIN_SEQ", "1")
    # _FLASH_MIN_SEQ is read at import; patch the module constant too
    from paddle_tpu.ops import nn_functional as NF
    monkeypatch.setattr(NF, "_FLASH_MIN_SEQ", 1)

    pt.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=16, dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    ids = np.zeros((1, 16), np.int32)
    float(m(pt.to_tensor(ids), labels=pt.to_tensor(ids)))  # no boom


def test_sdpa_causal_kv_cache_never_uses_flash(monkeypatch):
    """Causal attention with sq != sk (a concatenated KV cache) must not
    route to the flash kernel: its diagonal-aligned causal mask has no
    cache-length offset (regression: silent wrong outputs in the GPT
    dynamic-cache path with use_flash forced)."""
    import paddle_tpu.ops.pallas.flash_attention as fa
    from paddle_tpu.ops import nn_functional as NF

    monkeypatch.setattr(fa, "flash_attention_supported",
                        lambda *a, **k: True)

    def boom(*a, **k):
        raise AssertionError("flash taken for causal sq != sk")

    monkeypatch.setattr(fa, "flash_attention", boom)
    q = jnp.zeros((1, 128, 2, 64))
    kv = jnp.zeros((1, 256, 2, 64))
    out = NF.scaled_dot_product_attention(q, kv, kv, is_causal=True,
                                          use_flash=True)
    assert out.shape == q.shape
    # and the XLA path applies the cache offset: the first new token
    # (global position 128) must see all 129 visible keys, not just 1
    qv = jnp.ones((1, 1, 1, 4))
    kvv = jnp.asarray(
        np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1) *
        jnp.ones((1, 8, 1, 4)))
    got = NF.scaled_dot_product_attention(qv, kvv, kvv, is_causal=True,
                                          use_flash=False)
    assert float(got[0, 0, 0, 0]) > 0  # attends beyond position 0


@pytest.mark.parametrize("causal", [False, True])
def test_fused_single_qblock_backward_multi_kblock(causal):
    """The nq==1 fused backward with nk>1 (cross-attention: short Q,
    long K): dQ must accumulate across the streamed K blocks and dK/dV
    must land in the right per-block slots — including the causal
    branch, where the second K block is FULLY masked (its dk/dv must
    come out exactly zero via the skip path, not garbage). Reachable
    in production via q_len<=block <= k_len cross-attention."""
    rng = np.random.default_rng(7)
    b, h, d = 2, 2, 64
    sq, sk = 128, 256  # block 128 -> nq=1, nk=2 through the fused path
    q = jnp.asarray(rng.standard_normal((b, sq, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, sk, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, sk, h, d)).astype(np.float32))

    def loss_flash(q_, k_, v_):
        return jnp.sum(fa.flash_attention(
            q_, k_, v_, causal=causal, block_q=128, block_k=128) ** 2)

    def loss_ref(q_, k_, v_):
        # the flash causal mask is diagonal-aligned (q_pos >= k_pos,
        # no cache offset) — mirror it for the reference
        o = scaled_dot_product_attention(q_, k_, v_, use_flash=False,
                                         attn_mask=_diag_mask(sq, sk)
                                         if causal else None)
        return jnp.sum(o ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"d{name} mismatch")
    if causal:
        # K block 1 (positions 128..255) is fully masked: its dk/dv
        # must be EXACT zeros (the pl.when skip writes them)
        assert np.all(np.asarray(g_flash[1])[:, 128:] == 0.0)
        assert np.all(np.asarray(g_flash[2])[:, 128:] == 0.0)


def _diag_mask(sq, sk):
    """Diagonal-aligned causal mask (the flash kernel's convention:
    q_pos >= k_pos with no sk-sq cache offset)."""
    qpos = jnp.arange(sq)[:, None]
    kpos = jnp.arange(sk)[None, :]
    return jnp.where(qpos >= kpos, 0.0, -jnp.inf)[None, None]


def test_single_kblock_causal_forward_sq_gt_sk():
    """nq>1/nk==1 causal single-K-block forward (the qb-offset mask
    lines in _fwd_single_block_kernel): q longer than k, grid over Q
    blocks, every block sees the one K block under the diagonal-aligned
    mask."""
    rng = np.random.default_rng(11)
    b, h, d = 1, 2, 64
    sq, sk = 256, 128  # block 128 -> nq=2, nk=1 single-block fwd path
    q = jnp.asarray(rng.standard_normal((b, sq, h, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((b, sk, h, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, sk, h, d)).astype(np.float32))
    out = fa.flash_attention(q, k, v, causal=True, block_q=128,
                             block_k=128)
    ref = scaled_dot_product_attention(q, k, v, use_flash=False,
                                       attn_mask=_diag_mask(sq, sk))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
