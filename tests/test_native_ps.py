"""Native (C++) parameter-server transport tests.

Reference parity: brpc PS service (service/brpc_ps_server.cc /
brpc_ps_client.cc) — here native/pt_ps.cc over POSIX sockets with
server-side table math, driven through the same client surface the
Python-transport PSClient exposes.
"""

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.distributed.ps import (
    AsyncCommunicator, GeoCommunicator, NativePSClient, NativePSServer)

pytestmark = pytest.mark.skipif(
    native.get_lib() is None or not hasattr(native.get_lib() or object(),
                                            "pt_ps_server_create"),
    reason="native toolchain unavailable")


def _cluster(n_servers, dense=(), sparse=(), **kw):
    servers = []
    for _ in range(n_servers):
        s = NativePSServer()
        for name, shape, opt in dense:
            s.add_dense_table(name, shape, optimizer=opt, lr=0.1)
        for name, dim in sparse:
            s.add_sparse_table(name, dim, lr=0.05, **kw)
        s.start()
        servers.append(s)
    client = NativePSClient([s.endpoint for s in servers])
    return servers, client


def _teardown(servers, client):
    client.stop()
    for s in servers:
        s.stop()


def test_dense_sgd_and_adam_server_side():
    servers, cli = _cluster(
        2, dense=[("w_sgd", (3, 4), "sgd"), ("w_adam", (5,), "adam")])
    try:
        w = np.random.default_rng(0).standard_normal((3, 4)).astype(
            np.float32)
        cli.push_dense_init("w_sgd", w)
        g = np.ones((3, 4), np.float32)
        cli.push_dense_grad("w_sgd", g)
        # server-side SGD: w - lr*g
        np.testing.assert_allclose(
            cli.pull_dense("w_sgd").reshape(3, 4), w - 0.1 * g, rtol=1e-6)

        cli.push_dense_init("w_adam", np.zeros(5, np.float32))
        for _ in range(3):
            cli.push_dense_grad("w_adam", np.ones(5, np.float32))
        v = cli.pull_dense("w_adam")
        assert (v < 0).all() and np.isfinite(v).all()
    finally:
        _teardown(servers, cli)


def test_sparse_shard_across_servers():
    servers, cli = _cluster(3, sparse=[("emb", 16)])
    try:
        keys = np.arange(30, dtype=np.int64)
        rows = cli.pull_sparse("emb", keys)
        assert rows.shape == (30, 16)
        # deterministic per-key init: a re-pull returns identical rows
        np.testing.assert_allclose(cli.pull_sparse("emb", keys), rows)
        # rows land on key % 3 servers
        per_server = []
        for s in servers:
            c = NativePSClient([s.endpoint])
            per_server.append(c.sparse_size("emb"))
            c.close()
        assert sum(per_server) == 30 and all(n == 10 for n in per_server)

        cli.push_sparse_grad("emb", keys, np.ones((30, 16), np.float32))
        rows2 = cli.pull_sparse("emb", keys)
        assert (rows2 < rows).all()  # adagrad step moved against +grad
    finally:
        _teardown(servers, cli)


def test_push_pull_roundtrip_matches_python_table_math():
    """C++ adagrad matches the Python SparseTable update rule."""
    servers, cli = _cluster(1, sparse=[("emb", 4)])
    try:
        keys = np.array([7], np.int64)
        r0 = cli.pull_sparse("emb", keys)[0]
        g = np.full(4, 0.25, np.float32)
        cli.push_sparse_grad("emb", keys, g[None])
        r1 = cli.pull_sparse("emb", keys)[0]
        expected = r0 - 0.05 * g / (np.sqrt(g * g) + 1e-6)
        np.testing.assert_allclose(r1, expected, rtol=1e-5)
    finally:
        _teardown(servers, cli)


def test_geo_communicator_over_native_client():
    servers, cli = _cluster(2, sparse=[("emb", 8)])
    try:
        geo = GeoCommunicator(cli, "emb", emb_dim=8, k_steps=2, lr=0.1)
        keys = np.array([1, 2, 3], np.int64)
        for _ in range(4):
            rows = geo.pull(keys)
            geo.push_grad(keys, np.ones((3, 8), np.float32) * 0.1)
        geo.sync()
        server_rows = cli.pull_sparse("emb", keys)
        np.testing.assert_allclose(server_rows, geo.pull(keys), atol=1e-6)
    finally:
        _teardown(servers, cli)


def test_async_communicator_over_native_client():
    servers, cli = _cluster(
        1, dense=[("w", (4,), "sgd")])
    try:
        cli.push_dense_init("w", np.zeros(4, np.float32))
        comm = AsyncCommunicator(cli, send_wait_s=0.005)
        comm.start()
        for _ in range(10):
            comm.push("w", np.ones(4, np.float32))
        comm.stop()
        w = cli.pull_dense("w")
        np.testing.assert_allclose(w, -0.1 * 10 * np.ones(4), rtol=1e-5)
    finally:
        _teardown(servers, cli)


def test_concurrent_clients():
    import threading

    servers, cli = _cluster(2, sparse=[("emb", 8)])
    try:
        errs = []

        def worker(seed):
            try:
                c = NativePSClient([s.endpoint for s in servers])
                rng = np.random.default_rng(seed)
                for _ in range(20):
                    keys = rng.integers(0, 100, size=16).astype(np.int64)
                    c.pull_sparse("emb", keys)
                    c.push_sparse_grad(
                        "emb", keys,
                        rng.standard_normal((16, 8)).astype(np.float32))
                c.close()  # disconnect without stopping the server
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        assert cli.sparse_size("emb") > 0
    finally:
        _teardown(servers, cli)
