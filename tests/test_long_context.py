"""Long-context training through model-level sequence parallelism.

The capability the reference LACKS (SURVEY §5: no ring attention /
context parallel anywhere in the tree) and this framework must exceed it
on: GPT with seq_parallel_mode='ring'/'ulysses' trains with the sequence
axis sharded over the mesh, matching the dense single-device model."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
from paddle_tpu.distributed import DistributedStrategy, fleet
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM

pytestmark = pytest.mark.slow  # convergence-scale runtime


@pytest.fixture(scope="module", autouse=True)
def sep_env():
    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "sep_degree": 8}
    fleet.init(strategy=s)
    yield


def _cfg(seq_mode, s=256, heads=4):
    # ulysses redistributes heads over the sep axis, so heads must
    # divide by the sep degree (8)
    return GPTConfig(vocab_size=211, hidden_size=32, num_layers=2,
                     num_heads=heads, max_seq_len=s, dropout=0.0,
                     attn_dropout=0.0, seq_parallel_mode=seq_mode)


@pytest.mark.parametrize("mode", ["ring", "ulysses", "zigzag"])
def test_gpt_sequence_parallel_matches_dense(mode):
    """Model-level sp: the sep-sharded train step's losses track the
    dense single-device model step-for-step."""
    ids = (np.arange(2 * 256).reshape(2, 256) % 211).astype(np.int32)

    heads = 8 if mode == "ulysses" else 4
    pt.seed(7)
    dense = GPTForCausalLM(_cfg(None, heads=heads))
    s1 = TrainStep(dense, optim.SGD(learning_rate=0.1),
                   lambda m, b: m(b[0], labels=b[1]))
    l1 = [float(s1((ids, ids))) for _ in range(3)]

    pt.seed(7)
    sp_model = GPTForCausalLM(_cfg(mode, heads=heads))
    s2 = fleet.distributed_jit(sp_model, optim.SGD(learning_rate=0.1),
                               lambda m, b: m(b[0], labels=b[1]))
    l2 = [float(s2((ids, ids))) for _ in range(3)]

    np.testing.assert_allclose(l1, l2, rtol=5e-3, atol=5e-4)


def test_long_sequence_forward_8k():
    """S=8192 forward over sep=8 (1024 positions per rank) — the
    long-context configuration the reference cannot express at all."""
    cfg = _cfg("ring", s=8192)
    pt.seed(0)
    model = GPTForCausalLM(cfg)
    step = fleet.distributed_jit(model, optim.SGD(learning_rate=0.05),
                                 lambda m, b: m(b[0], labels=b[1]))
    ids = (np.arange(1 * 8192).reshape(1, 8192) % 211).astype(np.int32)
    first = float(step((ids, ids)))
    second = float(step((ids, ids)))
    assert np.isfinite(first) and np.isfinite(second)
    assert second < first


def _cfg_mp(seq_mode, heads):
    # vocab must divide mp=2 (VocabParallelEmbedding shards the vocab dim)
    return GPTConfig(vocab_size=212, hidden_size=32, num_layers=2,
                     num_heads=heads, max_seq_len=256, dropout=0.0,
                     attn_dropout=0.0, seq_parallel_mode=seq_mode)


def _dense_losses(heads, ids, steps=3):
    pt.seed(7)
    dense = GPTForCausalLM(_cfg_mp(None, heads))
    dense.eval()
    s1 = TrainStep(dense, optim.SGD(learning_rate=0.1),
                   lambda m, b: m(b[0], labels=b[1]))
    return [float(s1((ids, ids))) for _ in range(steps)]


@pytest.mark.parametrize("mode", ["ring", "ulysses", "zigzag"])
def test_sequence_parallel_composes_with_mp(mode):
    """sep x mp x dp in one mesh: ring/ulysses attention over mp-sharded
    heads (the r2 NotImplementedError, now closed): losses track the
    dense model step-for-step."""
    ids = (np.arange(2 * 256).reshape(2, 256) % 211).astype(np.int32)
    heads = 8 if mode == "ulysses" else 4  # H/mp must divide sep
    want = _dense_losses(heads, ids)

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "sep_degree": 2}
    fleet.init(strategy=s)
    pt.seed(7)
    sp_model = GPTForCausalLM(_cfg_mp(mode, heads))
    sp_model.eval()
    s2 = fleet.distributed_jit(sp_model, optim.SGD(learning_rate=0.1),
                               lambda m, b: m(b[0], labels=b[1]))
    got = [float(s2((ids, ids))) for _ in range(3)]
    np.testing.assert_allclose(want, got, rtol=5e-3, atol=5e-4)


def test_sequence_parallel_inside_pipeline_stage():
    """pp x mp x sep: ring attention nested (partial-manual shard_map
    over sep+mp) inside the pipeline's manual-pp stage."""
    from paddle_tpu.distributed.topology import (
        get_hybrid_communicate_group)
    from paddle_tpu.models.gpt_pipeline import GPTPipelineTrainStep

    ids = (np.arange(2 * 256).reshape(2, 256) % 211).astype(np.int32)
    want = _dense_losses(4, ids)

    s = DistributedStrategy()
    s.hybrid_configs = {"pp_degree": 2, "mp_degree": 2, "sep_degree": 2}
    fleet.init(strategy=s)
    hcg = get_hybrid_communicate_group()
    pp = GPTPipelineTrainStep(_cfg_mp("ring", 4), optim.SGD(learning_rate=0.1),
                              pp=2, n_micro=2, hcg=hcg, schedule="1f1b",
                              seed=7)
    got = [float(pp(ids, ids)) for _ in range(3)]
    np.testing.assert_allclose(want, got, rtol=5e-3, atol=5e-4)


def test_zigzag_inside_pipeline_stage():
    """pp x mp x sep with the balanced zigzag ring: the pipeline's
    embed stage permutes into the zigzag layout, blocks run the
    balanced causal ring, and the head un-permutes before the
    next-token shift — losses match the dense model."""
    from paddle_tpu.distributed.topology import (
        get_hybrid_communicate_group)
    from paddle_tpu.models.gpt_pipeline import GPTPipelineTrainStep

    ids = (np.arange(2 * 256).reshape(2, 256) % 211).astype(np.int32)
    want = _dense_losses(4, ids)

    s = DistributedStrategy()
    s.hybrid_configs = {"pp_degree": 2, "mp_degree": 2, "sep_degree": 2}
    fleet.init(strategy=s)
    hcg = get_hybrid_communicate_group()
    pp = GPTPipelineTrainStep(_cfg_mp("zigzag", 4),
                              optim.SGD(learning_rate=0.1),
                              pp=2, n_micro=2, hcg=hcg, schedule="1f1b",
                              seed=7)
    got = [float(pp(ids, ids)) for _ in range(3)]
    np.testing.assert_allclose(want, got, rtol=5e-3, atol=5e-4)
