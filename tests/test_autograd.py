"""Eager autograd (dygraph tape) tests.

Mirrors the reference's imperative-engine tests
(python/paddle/fluid/tests/unittests/test_imperative_basic.py,
test_imperative_auto_prune.py, test_inplace.py hook/retain tests).
"""

import numpy as np
import pytest

import paddle_tpu as pt


def test_backward_chain():
    x = pt.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2.0
    z = y + 1.0
    w = (z * z).sum()
    w.backward()
    # dw/dx = 2*z*2 = 4*(2x+1)
    np.testing.assert_allclose(x.grad.numpy(), 4 * (2 * np.array(
        [1.0, 2.0, 3.0]) + 1))


def test_grad_accumulation():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_prunes():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = pt.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_no_grad_context():
    x = pt.to_tensor([1.0], stop_gradient=False)
    with pt.no_grad():
        y = x * 2
    assert y.grad_node is None
    assert y.stop_gradient


def test_detach():
    x = pt.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    z = (d * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_retain_graph():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 8.0])


def test_double_backward_raises():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_grad_api():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x ** 3).sum()
    (gx,) = pt.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), 3 * np.array([1.0, 2.0]) ** 2)
    # grad() must not pollute .grad
    assert x.grad is None


def test_grad_api_unused():
    x = pt.to_tensor([1.0], stop_gradient=False)
    u = pt.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).sum()
    with pytest.raises(RuntimeError):
        pt.grad(y, [u])
    (g,) = pt.grad((x * 2).sum(), [u], allow_unused=True)
    assert g is None


def test_hooks():
    x = pt.to_tensor([1.0, 1.0], stop_gradient=False)
    seen = {}

    def hook(g):
        seen["g"] = np.asarray(g)
        return g * 10

    x.register_hook(hook)
    (x * 2).sum().backward()
    np.testing.assert_allclose(seen["g"], [2.0, 2.0])
    np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])


def test_retain_grads_intermediate():
    x = pt.to_tensor([2.0], stop_gradient=False)
    y = x * 3
    y.retain_grads()
    (y * y).sum().backward()
    np.testing.assert_allclose(y.grad.numpy(), [12.0])


def test_multi_output_op_grad():
    x = pt.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                     stop_gradient=False)
    vals, idx = pt.topk(x, 2)
    vals.sum().backward()
    expect = np.zeros((2, 3), np.float32)
    expect[0, 2] = expect[0, 1] = 1
    expect[1, 2] = expect[1, 1] = 1
    np.testing.assert_allclose(x.grad.numpy(), expect)


def test_backward_through_getitem_setitem():
    x = pt.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x[1:]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])


def test_branching_graph():
    x = pt.to_tensor([1.0], stop_gradient=False)
    a = x * 2
    b = x * 3
    (a + b).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_nonscalar_backward_seed():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    y.backward(pt.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])


def test_deep_chain_no_recursion():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = x
    for _ in range(300):
        y = y + 0.001
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0])
