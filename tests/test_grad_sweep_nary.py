"""Numeric-gradient sweep over multi-input (n-ary) registry ops.

Reference parity: OpTest.check_grad (unittests/op_test.py:1405) verifies
analytic grads against finite differences for essentially every op,
including multi-input ones (matmul family, convs, norms, losses,
attention). tests/test_grad_sweep.py mechanizes the unary slice; this
file covers the n-ary slice through declarative input factories: each op
gets a concrete argument tuple plus the indices of the arguments whose
gradients are checked (labels/indices/shape args are held constant).
"""

import inspect
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.registry import all_ops

pytestmark = pytest.mark.slow  # exhaustive sweep; fast lane has smokes


def _rng(name):
    return np.random.default_rng(zlib.crc32(name.encode()))


def _f(rng, *shape, lo=0.2, hi=0.8):
    return jnp.asarray(rng.uniform(lo, hi, shape).astype(np.float32))


# --------------------------------------------------------------------------
# Factories: name -> fn(rng) -> (args tuple, diff_argnums tuple)
# --------------------------------------------------------------------------

def _binary_same(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4)), (0, 1)


def _binary_gapped(rng):
    """Pair with a guaranteed elementwise gap (no tie flips under FD)."""
    x = _separated(rng, 3, 4, scale=0.5)
    sign = jnp.asarray(rng.choice([-1.0, 1.0], (3, 4)).astype(np.float32))
    return (x, x + 0.2 * sign), (0, 1)


def _binary_x_only(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4, lo=1.0, hi=2.0)), (0,)


def _with_static(*static, diff=(0,), shape=(3, 4), lo=0.2, hi=0.8):
    def fac(rng):
        return (_f(rng, *shape, lo=lo, hi=hi),) + tuple(static), diff
    return fac


def _img(rng, c=3, h=6, w=6, n=2):
    return _f(rng, n, c, h, w)


def _float_label_loss(shape=(4, 5), label01=False):
    def fac(rng):
        x = _f(rng, *shape)
        lab = _f(rng, *shape)
        if label01:
            lab = jnp.clip(lab, 0.05, 0.95)
        return (x, lab), (0,)
    return fac


def _int_label_loss(classes=5, rows=4):
    def fac(rng):
        x = _f(rng, rows, classes, lo=-1.0, hi=1.0)
        lab = jnp.asarray(rng.integers(0, classes, (rows,)))
        return (x, lab), (0,)
    return fac


def _rnn_cell(with_c=False):
    def fac(rng):
        x = _f(rng, 2, 4)
        h = _f(rng, 2, 8)
        args = [x, h]
        if with_c:
            args.append(_f(rng, 2, 8))
        gates = 4 if with_c else (3 if "gru" else 1)
        return args, None  # replaced per-op below
    return fac


def fac_matmul(rng):
    return (_f(rng, 3, 4), _f(rng, 4, 5)), (0, 1)


def fac_bmm(rng):
    return (_f(rng, 2, 3, 4), _f(rng, 2, 4, 5)), (0, 1)


def fac_addmm(rng):
    return (_f(rng, 3, 5), _f(rng, 3, 4), _f(rng, 4, 5)), (0, 1, 2)


def fac_mv(rng):
    return (_f(rng, 3, 4), _f(rng, 4)), (0, 1)


def fac_outer(rng):
    return (_f(rng, 3), _f(rng, 4)), (0, 1)


def fac_dot(rng):
    return (_f(rng, 4), _f(rng, 4)), (0, 1)


def fac_linear(rng):
    return (_f(rng, 3, 4), _f(rng, 4, 5)), (0, 1)


def fac_bilinear(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 5), _f(rng, 2, 4, 5)), (0, 1, 2)


def fac_conv2d(rng):
    return (_img(rng), _f(rng, 4, 3, 3, 3)), (0, 1)


def fac_conv1d(rng):
    return (_f(rng, 2, 3, 8), _f(rng, 4, 3, 3)), (0, 1)


def fac_conv3d(rng):
    return (_f(rng, 1, 2, 4, 4, 4), _f(rng, 3, 2, 2, 2, 2)), (0, 1)


def fac_prelu(rng):
    return (_f(rng, 2, 3, 4, 4, lo=-1.0, hi=1.0), _f(rng, 3)), (0, 1)


def fac_deformable_conv(rng):
    # offset zero-ish keeps the bilinear sampling in a smooth region
    x = _img(rng, c=2, h=5, w=5, n=1)
    offset = _f(rng, 1, 2 * 3 * 3, 3, 3, lo=-0.1, hi=0.1)
    w = _f(rng, 4, 2, 3, 3)
    return (x, offset, w), (0, 2)


def fac_embedding(rng):
    ids = jnp.asarray(rng.integers(0, 6, (2, 3)))
    return (ids, _f(rng, 6, 4)), (1,)


def fac_batch_norm(rng):
    x = _img(rng)
    return (x, jnp.zeros(3), jnp.ones(3)), (0,)


def fac_layer_norm(rng):
    return (_f(rng, 3, 4), (4,)), (0,)


def fac_group_norm(rng):
    return (_img(rng, c=4), 2), (0,)


def fac_sdpa(rng):
    q = _f(rng, 2, 4, 2, 4)
    k = _f(rng, 2, 4, 2, 4)
    v = _f(rng, 2, 4, 2, 4)
    return (q, k, v), (0, 1, 2)


def fac_gather(rng):
    return (_f(rng, 5, 4), jnp.asarray([0, 2, 3])), (0,)


def fac_take_along_axis(rng):
    idx = jnp.asarray(rng.integers(0, 3, (3, 4)))
    return (_f(rng, 3, 4), idx, 0), (0,)


def fac_scatter(rng):
    return (_f(rng, 5, 4), jnp.asarray([0, 2]), _f(rng, 2, 4)), (0, 2)


def fac_scatter_nd_add(rng):
    return (_f(rng, 5, 4), jnp.asarray([[0], [2]]), _f(rng, 2, 4)), (0, 2)


def fac_put_along_axis(rng):
    idx = jnp.asarray(rng.integers(0, 3, (1, 4)))
    return (_f(rng, 3, 4), idx, _f(rng, 1, 4), 0), (0, 2)


def fac_index_select(rng):
    return (_f(rng, 5, 4), jnp.asarray([0, 3])), (0,)


def fac_index_sample(rng):
    idx = jnp.asarray(rng.integers(0, 4, (3, 2)))
    return (_f(rng, 3, 4), idx), (0,)


def fac_index_add(rng):
    return (_f(rng, 5, 4), jnp.asarray([0, 2]), 0, _f(rng, 2, 4)), (0, 3)


def fac_index_fill(rng):
    return (_f(rng, 5, 4), jnp.asarray([0, 2]), 0, 0.5), (0,)


def fac_segment(rng):
    return (_f(rng, 6, 4), jnp.asarray([0, 0, 1, 1, 2, 2]), 3), (0,)


def fac_ctc(rng):
    lp = jax.nn.log_softmax(_f(rng, 6, 2, 5, lo=-1.0, hi=1.0))
    labels = jnp.asarray(rng.integers(1, 5, (2, 3)))
    return (lp, labels, jnp.asarray([6, 6]), jnp.asarray([3, 3])), (0,)


def fac_nll(rng):
    x = jax.nn.log_softmax(_f(rng, 4, 5, lo=-1.0, hi=1.0))
    return (x, jnp.asarray(rng.integers(0, 5, (4,)))), (0,)


def fac_hsigmoid(rng):
    return ((_f(rng, 3, 6), jnp.asarray(rng.integers(0, 8, (3,))),
             _f(rng, 7, 6), None, 8), (0, 2))


def fac_center_loss(rng):
    return ((_f(rng, 4, 6), jnp.asarray(rng.integers(0, 3, (4,))),
             _f(rng, 3, 6)), (0,))


def fac_triplet(rng):
    return (_f(rng, 4, 6), _f(rng, 4, 6), _f(rng, 4, 6)), (0, 1, 2)


def fac_margin_rank(rng):
    lab = jnp.asarray(rng.choice([-1.0, 1.0], 4).astype(np.float32))
    return (lab, _f(rng, 4), _f(rng, 4)), (1, 2)


def fac_margin_ranking(rng):
    lab = jnp.asarray(rng.choice([-1.0, 1.0], 4).astype(np.float32))
    return (_f(rng, 4), _f(rng, 4), lab), (0, 1)


def fac_cosine_embedding(rng):
    lab = jnp.asarray(rng.choice([-1.0, 1.0], 3).astype(np.float32))
    return (_f(rng, 3, 5), _f(rng, 3, 5), lab), (0, 1)


def fac_npair(rng):
    return ((_f(rng, 3, 5), _f(rng, 3, 5),
             jnp.asarray(rng.integers(0, 3, (3,)))), (0, 1))


def fac_gaussian_nll(rng):
    return ((_f(rng, 4, 3), _f(rng, 4, 3),
             _f(rng, 4, 3, lo=0.5, hi=1.0)), (0, 1, 2))


def fac_roi(rng):
    x = _separated(rng, 2, 8, 8, scale=0.1)
    rois = jnp.asarray([[0.0, 0.0, 6.0, 6.0], [1.0, 1.0, 7.0, 7.0]],
                       jnp.float32)
    return (x, rois, 4), (0,)


def fac_psroi(rng):
    x = _img(rng, c=8, h=6, w=6, n=1)
    rois = jnp.asarray([[0.0, 0.0, 5.0, 5.0]], jnp.float32)
    return (x, rois, 2, 1.0, 2, 2), (0,)


def fac_prroi(rng):
    x = _img(rng, c=2, h=6, w=6, n=1)
    rois = jnp.asarray([[0.0, 0.0, 5.0, 5.0]], jnp.float32)
    return (x, rois, 1.0, 2, 2), (0,)


def fac_grid_sample(rng):
    x = _img(rng, c=2, h=5, w=5, n=1)
    grid = _f(rng, 1, 4, 4, 2, lo=-0.8, hi=0.8)
    return (x, grid), (0, 1)


def fac_iou(rng):
    a = _f(rng, 3, 4, lo=0.0, hi=5.0)
    a = a.at[:, 2:].add(6.0)
    b = _f(rng, 2, 4, lo=0.0, hi=5.0)
    b = b.at[:, 2:].add(6.0)
    return (a, b), (0,)


def fac_box_clip(rng):
    b = _f(rng, 3, 4, lo=1.0, hi=8.0)
    return (b, (10.0, 10.0)), (0,)


def fac_box_coder(rng):
    priors = _f(rng, 3, 4, lo=0.0, hi=4.0)
    priors = priors.at[:, 2:].add(5.0)
    targets = _f(rng, 2, 4, lo=0.0, hi=4.0)
    targets = targets.at[:, 2:].add(5.0)
    return (priors, None, targets), (0, 2)


def fac_lerp(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4), 0.3), (0, 1)


def fac_addcdiv(rng):
    return ((_f(rng, 3, 4), _f(rng, 3, 4),
             _f(rng, 3, 4, lo=0.5, hi=1.5)), (0, 1, 2))


def fac_solve(rng):
    a = _f(rng, 4, 4)
    a = a @ a.T + 4.0 * jnp.eye(4)
    return (a, _f(rng, 4, 2)), (0, 1)


def fac_triangular_solve(rng):
    a = jnp.tril(_f(rng, 4, 4, lo=0.5, hi=1.5)) + 2.0 * jnp.eye(4)
    return (a, _f(rng, 4, 2)), (0, 1)


def fac_cholesky_solve(rng):
    a = _f(rng, 4, 4)
    chol = jnp.linalg.cholesky(a @ a.T + 4.0 * jnp.eye(4))
    return (_f(rng, 4, 2), chol), (0,)


def fac_householder(rng):
    return (_f(rng, 4, 3), _f(rng, 3, lo=0.1, hi=0.4)), (0, 1)


def fac_tensordot(rng):
    return (_f(rng, 3, 4), _f(rng, 4, 5)), (0, 1)


def fac_unpool(rng):
    x = _f(rng, 1, 1, 2, 2)
    idx = jnp.asarray([[[[0, 3], [8, 11]]]])
    return (x, idx, 2), (0,)


def fac_max_unpool2d(rng):
    x = _f(rng, 1, 1, 2, 2)
    idx = jnp.asarray([[[[0, 3], [8, 11]]]])
    return (x, idx, 2), (0,)


def fac_fold(rng):
    return (_f(rng, 1, 4, 4), (3, 3), (2, 2)), (0,)


def fac_sequence_xy(diff=(0,), with_dim=True):
    def fac(rng):
        shape = (2, 5, 3) if with_dim else (2, 5)
        return (_f(rng, *shape), jnp.asarray([4, 2])), diff
    return fac


def fac_sequence_conv(rng):
    return ((_f(rng, 2, 5, 4), jnp.asarray([4, 2]), _f(rng, 12, 5), 3),
            (0, 2))


def fac_warpctc(rng):
    lp = jax.nn.log_softmax(_f(rng, 6, 2, 5, lo=-1.0, hi=1.0))
    labels = jnp.asarray(rng.integers(1, 5, (2, 3)))
    return (lp, labels, jnp.asarray([6, 6]), jnp.asarray([3, 3])), (0,)


def fac_linear_chain_crf(rng):
    em = _f(rng, 1, 5, 3)
    tr = _f(rng, 5, 3)
    lab = jnp.asarray(rng.integers(0, 3, (1, 5)))
    return (em, tr, lab), (0, 1)


def fac_rank_attention(rng):
    x = _f(rng, 3, 4)
    # rank_offset: [N, 1 + 2*max_rank] int (ins rank, then (rank, index))
    ro = jnp.asarray(rng.integers(0, 2, (3, 5)))
    rp = _f(rng, 16, 4)
    return (x, ro, rp, 2), (0,)


def fac_tree_conv(rng):
    nodes = _f(rng, 1, 4, 3)
    edges = jnp.asarray([[[0, 1], [1, 2], [2, 3]]])
    filt = _f(rng, 3, 2, 4)
    return (nodes, edges, filt), (0, 2)


def fac_match_matrix(rng):
    return ((_f(rng, 1, 4, 3), _f(rng, 1, 5, 3), _f(rng, 3, 2, 3)),
            (0, 1, 2))


def fac_var_conv_2d(rng):
    x = _f(rng, 2, 1, 6, 6)
    return ((x, jnp.asarray([6, 6]), jnp.asarray([6, 6]),
             _f(rng, 1, 1, 3, 3), 1, 1, 3), (0, 3))


def fac_im2sequence(rng):
    return (_img(rng, c=1, h=6, w=6, n=1), (2, 2)), (0,)


def fac_temporal_shift(rng):
    return (_f(rng, 4, 4, 3, 3), 2), (0,)


def fac_cvm(rng):
    return (_f(rng, 3, 6), _f(rng, 3, 2, lo=1.0, hi=2.0)), (0,)


def fac_data_norm(rng):
    x = _f(rng, 4, 3)
    return ((x, jnp.full((3,), 10.0), jnp.full((3,), 5.0),
             jnp.full((3,), 8.0)), (0,))


def fac_affine_channel(rng):
    return (_img(rng), _f(rng, 3), _f(rng, 3)), (0, 1, 2)


def fac_affine_grid(rng):
    theta = _f(rng, 1, 2, 3)
    return (theta, (1, 1, 4, 4)), (0,)


def fac_bce_logits(rng):
    x = _f(rng, 4, 5, lo=-1.0, hi=1.0)
    lab = jnp.clip(_f(rng, 4, 5), 0.05, 0.95)
    return (x, lab), (0,)


def fac_sigmoid_focal(rng):
    x = _f(rng, 4, 5, lo=-1.0, hi=1.0)
    lab = (jnp.sign(_f(rng, 4, 5) - 0.5) * 0.5 + 0.5)
    return (x, lab), (0,)


def fac_softmax_ce(rng):
    x = _f(rng, 4, 5, lo=-1.0, hi=1.0)
    lab = jnp.asarray(rng.integers(0, 5, (4, 1)))
    return (x, lab), (0,)


def fac_cell(gates, with_c=False):
    def fac(rng):
        x, h = _f(rng, 2, 4), _f(rng, 2, 5)
        args = [x, h]
        if with_c:
            args.append(_f(rng, 2, 5))
        args += [_f(rng, gates * 5, 4), _f(rng, gates * 5, 5),
                 _f(rng, gates * 5), _f(rng, gates * 5)]
        return tuple(args), (0, 1) + tuple(
            range(2 + int(with_c), 6 + int(with_c)))
    return fac


def fac_maxout(rng):
    return (_f(rng, 2, 4, 3, 3), 2), (0,)


def fac_lp_pool(rng):
    return (_img(rng, c=2, h=4, w=4, n=1), 2.0, 2), (0,)


def fac_fsp(rng):
    return (_f(rng, 1, 2, 4, 4), _f(rng, 1, 3, 4, 4)), (0, 1)


def fac_bpr(rng):
    x = _f(rng, 4, 5, lo=-1.0, hi=1.0)
    return (x, jnp.asarray(rng.integers(0, 5, (4,)))), (0,)


def fac_teacher_student(rng):
    return (_f(rng, 4, 1, lo=-1.0, hi=1.0), _f(rng, 4, 1)), (0,)


def fac_nce(rng):
    return ((_f(rng, 3, 6), jnp.asarray(rng.integers(0, 8, (3, 1))),
             _f(rng, 8, 6)), {"key": jax.random.key(0)}, (0, 2))


def fac_sample_logits(rng):
    return ((_f(rng, 3, 8, lo=-1.0, hi=1.0),
             jnp.asarray(rng.integers(0, 8, (3, 1))), 4,
             jax.random.key(0)), (0,))


def fac_pad_constant_like(rng):
    return (_f(rng, 4, 5), _f(rng, 3, 4)), (1,)


def fac_conv_shift(rng):
    return (_f(rng, 2, 8), _f(rng, 2, 3)), (0, 1)


def fac_row_conv(rng):
    return (_f(rng, 2, 6, 4), _f(rng, 3, 4)), (0, 1)


def fac_batch_fc(rng):
    return (_f(rng, 2, 3, 4), _f(rng, 2, 4, 5)), (0, 1)


def fac_multiply_sum(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4)), (0, 1)


def fac_channel_ops(rng):
    return (_img(rng, c=4), 2), (0,)


def fac_pixel_shuffle(rng):
    return (_img(rng, c=4, h=3, w=3, n=1), 2), (0,)


def fac_pixel_unshuffle(rng):
    return (_img(rng, c=1, h=4, w=4, n=1), 2), (0,)


def fac_space_to_depth(rng):
    return (_img(rng, c=1, h=4, w=4, n=1), 2), (0,)


def _separated(rng, *shape, scale=1.0):
    n = int(np.prod(shape))
    vals = np.linspace(0.2, 0.2 + scale * n, n, dtype=np.float32)
    return jnp.asarray(rng.permutation(vals).reshape(shape))


def fac_kthvalue(rng):
    return (_separated(rng, 3, 5), 2), (0,)


def fac_quantile(rng):
    return (_separated(rng, 3, 5), 0.4), (0,)


def fac_renorm(rng):
    return (_f(rng, 3, 4), 2.0, 0, 1.0), (0,)


def fac_topk(rng):
    return (_f(rng, 3, 5), 2), (0,)


def fac_cross(rng):
    return (_f(rng, 4, 3), _f(rng, 4, 3)), (0, 1)


def fac_cdist(rng):
    # well-spread points: pairwise distances O(1) keep the FD probe's
    # float32 cancellation below tolerance
    return (_f(rng, 3, 4, lo=0.0, hi=3.0),
            _f(rng, 5, 4, lo=4.0, hi=7.0)), (0, 1)


def fac_expand_as(rng):
    return (_f(rng, 1, 4), _f(rng, 3, 4)), (0,)


def fac_view_as(rng):
    return (_f(rng, 3, 4), _f(rng, 4, 3)), (0,)


def fac_huber(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4)), (0,)


def fac_multi_label(rng):
    x = _f(rng, 3, 4, lo=-1.0, hi=1.0)
    lab = (jnp.sign(_f(rng, 3, 4) - 0.5) * 0.5 + 0.5)
    return (x, lab), (0,)


def fac_hinge_embedding(rng):
    lab = jnp.asarray(rng.choice([-1.0, 1.0], (3, 4)).astype(np.float32))
    return (_f(rng, 3, 4), lab), (0,)


def fac_hinge(rng):
    lab = jnp.asarray(rng.choice([0.0, 1.0], (3, 1)).astype(np.float32))
    return (_f(rng, 3, 1, lo=-1.0, hi=1.0), lab), (0,)


def fac_mod_huber(rng):
    lab = jnp.asarray(rng.choice([0.0, 1.0], (3, 1)).astype(np.float32))
    return (_f(rng, 3, 1, lo=-0.5, hi=0.5), lab), (0,)


def fac_dice(rng):
    x = jnp.clip(_f(rng, 3, 4), 0.05, 0.95)
    lab = jnp.asarray(rng.integers(0, 4, (3, 1)))
    return (x, lab), (0,)


def fac_log_loss(rng):
    x = jnp.clip(_f(rng, 4, 1), 0.1, 0.9)
    lab = jnp.asarray(rng.choice([0.0, 1.0], (4, 1)).astype(np.float32))
    return (x, lab), (0,)


def fac_poisson_nll(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4, lo=0.5, hi=2.0)), (0,)


def fac_kl(rng):
    x = jax.nn.log_softmax(_f(rng, 3, 4, lo=-1.0, hi=1.0))
    lab = jax.nn.softmax(_f(rng, 3, 4, lo=-1.0, hi=1.0))
    return (x, lab), (0,)


def fac_unflatten(rng):
    return (_f(rng, 3, 8), 1, (2, 4)), (0,)


def fac_as_strided(rng):
    return (_f(rng, 12), (3, 4), (4, 1)), (0,)


def fac_complexpolar(rng):
    return (_f(rng, 3, 4), _f(rng, 3, 4)), (0, 1)


FACTORIES = {
    # elementwise binary, both args smooth
    **{n: _binary_same for n in (
        "add", "subtract", "multiply", "divide", "atan2", "hypot",
        "logaddexp", "dist", "squared_l2_distance", "pairwise_distance",
        "cos_sim", "cosine_similarity", "kron")},
    **{n: _binary_gapped for n in ("maximum", "minimum", "fmax",
                                   "fmin")},
    "pow": lambda rng: ((_f(rng, 3, 4, lo=0.5, hi=1.5),
                         _f(rng, 3, 4, lo=0.5, hi=1.5)), (0, 1)),
    "float_power": lambda rng: ((_f(rng, 3, 4, lo=0.5, hi=1.5),
                                 _f(rng, 3, 4, lo=0.5, hi=1.5)), (0, 1)),
    **{n: _binary_x_only for n in (
        "mod", "remainder", "floor_mod", "copysign", "ldexp",
        "heaviside")},
    "polygamma": lambda rng: ((_f(rng, 3, 4, lo=1.0, hi=2.0), 1), (0,)),
    "lerp": fac_lerp, "addcdiv": fac_addcdiv, "addcmul": fac_addcdiv,
    "complex": fac_complexpolar, "complex_": fac_complexpolar,
    "polar": fac_complexpolar,
    # matmul family
    "matmul": fac_matmul, "mm": fac_matmul, "bmm": fac_bmm,
    "addmm": fac_addmm, "mv": fac_mv, "outer": fac_outer, "dot": fac_dot,
    "inner": lambda rng: ((_f(rng, 3, 4), _f(rng, 5, 4)), (0, 1)),
    "tensordot": lambda rng: ((_f(rng, 2, 3, 4), _f(rng, 3, 4, 5)),
                              (0, 1)),
    "mul": fac_matmul,
    "linear": fac_linear, "bilinear": fac_bilinear,
    "bilinear_tensor_product": fac_bilinear, "batch_fc": fac_batch_fc,
    "multiply_sum": fac_multiply_sum, "fsp": fac_fsp,
    # convs
    "conv1d": fac_conv1d, "conv2d": fac_conv2d, "conv3d": fac_conv3d,
    "conv1d_transpose": lambda rng: ((_f(rng, 2, 3, 8),
                                      _f(rng, 3, 4, 3)), (0, 1)),
    "conv2d_transpose": lambda rng: ((_img(rng),
                                      _f(rng, 3, 4, 3, 3)), (0, 1)),
    "conv3d_transpose": lambda rng: ((_f(rng, 1, 2, 4, 4, 4),
                                      _f(rng, 2, 3, 2, 2, 2)), (0, 1)),
    "deformable_conv": fac_deformable_conv, "row_conv": fac_row_conv,
    "conv_shift": fac_conv_shift, "prelu": fac_prelu,
    # norms
    "batch_norm": fac_batch_norm, "layer_norm": fac_layer_norm,
    "group_norm": fac_group_norm, "data_norm": fac_data_norm,
    "local_response_norm": _with_static(2, shape=(1, 4, 5, 5)),
    "affine_channel": fac_affine_channel,
    # attention / cells
    "scaled_dot_product_attention": fac_sdpa,
    "simple_rnn_cell": fac_cell(1), "gru_cell": fac_cell(3),
    "lstm_cell": fac_cell(4, with_c=True),
    # embedding / indexing
    "embedding": fac_embedding, "gather": fac_gather,
    "gather_nd": lambda rng: ((_f(rng, 4, 3),
                               jnp.asarray([[0], [2]])), (0,)),
    "take": fac_gather, "take_along_axis": fac_take_along_axis,
    "index_select": fac_index_select, "index_sample": fac_index_sample,
    "index_add": fac_index_add, "index_fill": fac_index_fill,
    "scatter": fac_scatter, "scatter_nd_add": fac_scatter_nd_add,
    "scatter_nd": lambda rng: ((jnp.asarray([[0], [2]]),
                                _f(rng, 2, 4), (5, 4)), (1,)),
    "put_along_axis": fac_put_along_axis,
    **{n: fac_segment for n in ("segment_sum", "segment_mean",
                                "segment_max", "segment_min")},
    "segment_pool": lambda rng: ((_f(rng, 6, 4),
                                  jnp.asarray([0, 0, 1, 1, 2, 2])),
                                 {"num_segments": 3}, (0,)),
    # losses: float-label
    **{n: _float_label_loss() for n in (
        "mse_loss", "l1_loss", "smooth_l1_loss", "huber_loss",
        "square_error_cost", "soft_margin_loss")},
    "huber_loss": fac_huber,
    "binary_cross_entropy": lambda rng: (
        (jnp.clip(_f(rng, 4, 5), 0.05, 0.95),
         jnp.clip(_f(rng, 4, 5), 0.05, 0.95)), (0,)),
    "bce_loss": lambda rng: (
        (jnp.clip(_f(rng, 4, 5), 0.05, 0.95),
         jnp.clip(_f(rng, 4, 5), 0.05, 0.95)), (0,)),
    "binary_cross_entropy_with_logits": fac_bce_logits,
    "sigmoid_focal_loss": fac_sigmoid_focal,
    "multi_label_soft_margin_loss": fac_multi_label,
    "hinge_embedding_loss": fac_hinge_embedding,
    "hinge_loss": fac_hinge, "modified_huber_loss": fac_mod_huber,
    "dice_loss": fac_dice, "log_loss": fac_log_loss,
    "poisson_nll_loss": fac_poisson_nll,
    "kl_div": fac_kl, "kldiv_loss": fac_kl,
    "gaussian_nll_loss": fac_gaussian_nll,
    # losses: int-label
    "cross_entropy": _int_label_loss(),
    "nll_loss": fac_nll, "bpr_loss": fac_bpr,
    "softmax_with_cross_entropy": fac_softmax_ce,
    "teacher_student_sigmoid_loss": fac_teacher_student,
    "ctc_loss": fac_ctc, "warpctc": fac_warpctc,
    "hsigmoid_loss": fac_hsigmoid, "nce": fac_nce,
    "center_loss": fac_center_loss,
    "triplet_margin_loss": fac_triplet,
    "margin_rank_loss": fac_margin_rank,
    "margin_ranking_loss": fac_margin_ranking,
    "rank_loss": fac_margin_rank,
    "cosine_embedding_loss": fac_cosine_embedding,
    "npair_loss": fac_npair,
    "linear_chain_crf": fac_linear_chain_crf,
    # pooling / shape ops with static args
    "avg_pool1d": _with_static(2, shape=(1, 2, 6)),
    "avg_pool2d": _with_static(2, shape=(1, 2, 6, 6)),
    "avg_pool3d": _with_static(2, shape=(1, 1, 4, 4, 4)),
    "max_pool1d": lambda rng: ((_separated(rng, 1, 2, 6), 2), (0,)),
    "max_pool2d": lambda rng: ((_separated(rng, 1, 2, 6, 6), 2), (0,)),
    "max_pool3d": lambda rng: ((_separated(rng, 1, 1, 4, 4, 4), 2),
                               (0,)),
    "adaptive_avg_pool1d": _with_static(2, shape=(1, 2, 6)),
    "adaptive_avg_pool2d": _with_static(2, shape=(1, 2, 6, 6)),
    "adaptive_avg_pool3d": _with_static(2, shape=(1, 1, 4, 4, 4)),
    "adaptive_max_pool1d": lambda rng: ((_separated(rng, 1, 2, 6), 2),
                                        (0,)),
    "adaptive_max_pool2d": lambda rng: ((_separated(rng, 1, 2, 6, 6), 2),
                                        (0,)),
    "adaptive_max_pool3d": lambda rng: (
        (_separated(rng, 1, 1, 4, 4, 4), 2), (0,)),
    "lp_pool2d": fac_lp_pool, "spp": lambda rng: ((_separated(rng, 1, 2, 8, 8), 2), (0,)),
    "maxout": lambda rng: ((_separated(rng, 2, 4, 3, 3), 2), (0,)),
    "unpool": fac_unpool, "max_unpool2d": fac_max_unpool2d,
    "fold": fac_fold, "unfold": _with_static((2, 2), shape=(1, 2, 4, 4)),
    "im2sequence": fac_im2sequence,
    "pixel_shuffle": fac_pixel_shuffle,
    "pixel_unshuffle": fac_pixel_unshuffle,
    "channel_shuffle": fac_channel_ops,
    "shuffle_channel": fac_channel_ops,
    "space_to_depth": fac_space_to_depth,
    "temporal_shift": fac_temporal_shift,
    # structural / static-arg ops (grad wrt x only)
    "broadcast_to": _with_static((3, 4), shape=(1, 4)),
    "expand": _with_static((3, 4), shape=(1, 4)),
    "expand_as": fac_expand_as,
    "reshape": _with_static((4, 3)), "view": _with_static((4, 3)),
    "view_as": fac_view_as,
    "tile": _with_static((2, 1)),
    "transpose": _with_static((1, 0)),
    "flip": _with_static(0), "reverse": _with_static(0),
    "roll": _with_static(1),
    "unsqueeze": _with_static(0), "chunk": _with_static(2),
    "split": _with_static(2, shape=(4, 4)),
    "tensor_split": _with_static(2, shape=(4, 4)),
    "hsplit": lambda rng: ((_f(rng, 4, 4), 2), (0,)),
    "vsplit": lambda rng: ((_f(rng, 4, 4), 2), (0,)),
    "dsplit": lambda rng: ((_f(rng, 2, 2, 4), 2), (0,)),
    "moveaxis": lambda rng: ((_f(rng, 3, 4), 0, 1), (0,)),
    "swapaxes": lambda rng: ((_f(rng, 3, 4), 0, 1), (0,)),
    "pad": _with_static((1, 1, 2, 0)),
    "pad3d": _with_static((1, 1, 1, 1, 1, 1), shape=(1, 2, 3, 3, 3)),
    "zeropad2d": _with_static((1, 1, 1, 1), shape=(1, 2, 3, 3)),
    "crop": _with_static((2, 3)),
    "unflatten": fac_unflatten, "as_strided": fac_as_strided,
    "kthvalue": fac_kthvalue,
    "topk": lambda rng: ((_separated(rng, 3, 5), 2), (0,)),
    "quantile": fac_quantile, "nanquantile": fac_quantile,
    "renorm": fac_renorm,
    "repeat_interleave": _with_static(2),
    "slice": _with_static((0,), (1,), (3,), diff=(0,), shape=(4, 4)),
    "strided_slice": _with_static((0,), (0,), (4,), (2,), shape=(4, 4)),
    "cross": fac_cross, "cdist": fac_cdist,
    "pad_constant_like": fac_pad_constant_like,
    # linalg solves
    "solve": fac_solve, "triangular_solve": fac_triangular_solve,
    "cholesky_solve": fac_cholesky_solve,
    "householder_product": fac_householder,
    "matrix_power": lambda rng: ((_f(rng, 3, 3) + 2 * jnp.eye(3), 2),
                                 (0,)),
    # vision/detection
    "grid_sample": fac_grid_sample, "roi_align": fac_roi,
    "roi_pool": fac_roi, "psroi_pool": fac_psroi,
    "prroi_pool": fac_prroi,
    "iou_similarity": fac_iou, "box_clip": fac_box_clip,
    "box_coder": fac_box_coder,
    "affine_grid": fac_affine_grid,
    "correlation": lambda rng: ((_img(rng, c=2, h=5, w=5, n=1),
                                 _img(rng, c=2, h=5, w=5, n=1),
                                 1, 1, 1), (0, 1)),
    "cvm": fac_cvm,
    # sequence (ragged) family: x + lengths
    **{n: fac_sequence_xy() for n in (
        "sequence_reverse", "sequence_pad", "sequence_pool",
        "sequence_first_step", "sequence_last_step")},
    "sequence_softmax": fac_sequence_xy(with_dim=False),
    "sequence_conv": fac_sequence_conv,
    "sequence_slice": lambda rng: ((_f(rng, 2, 5, 3),
                                    jnp.asarray([4, 3]), 1, 2), (0,)),
    # NLP/CTR tails
    "rank_attention": fac_rank_attention, "tree_conv": fac_tree_conv,
    "match_matrix_tensor": fac_match_matrix,
    "var_conv_2d": fac_var_conv_2d,
}

# n-ary ops deliberately not swept, with reasons
NARY_SKIP = {
    # discrete/boolean outputs — no gradient to check
    "allclose", "isclose", "equal_all", "searchsorted", "bucketize",
    "gcd", "lcm", "left_shift", "right_shift", "shard_index",
    "beam_search_step", "kthvalue_indices", "nextafter",
    # random draws / discrete accidental-hit masking
    "binomial", "random_crop", "sample_logits",
    # constant generators (no float input grads)
    "full", "full_like", "linspace", "logspace", "cast",
    "anchor_generator", "prior_box", "yolo_box", "yolov3_loss",
    "box_decoder_and_assign",
    # mask/index-driven selection: grads wrt values covered elsewhere
    "masked_fill", "masked_scatter", "index_put", "multiplex",
    "take", "lu_unpack", "lstsq",
    # composite drivers with dedicated tests
    "rnn", "pyramid_hash", "sequence_enumerate", "sequence_erase",
    "sequence_concat", "sequence_scatter", "sequence_topk_avg_pooling",
    "sequence_expand", "sequence_expand_as", "sequence_reshape",
    # integer-quotient / piecewise-constant: d/dx is 0 a.e. and the FD
    # probe straddles the jumps
    "floor_divide",
}


def _nary_ops():
    out = []
    for name, od in sorted(all_ops().items()):
        if not od.differentiable or od.dynamic_shape:
            continue
        try:
            sig = inspect.signature(od.fn)
        except (TypeError, ValueError):
            continue
        req = [p for p in sig.parameters.values()
               if p.default is inspect.Parameter.empty and
               p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
        if len(req) >= 2:
            out.append(name)
    return out


NARY = _nary_ops()
SWEPT = [n for n in NARY if n in FACTORIES]


def test_every_nary_op_is_classified():
    """Every multi-input differentiable op either has an input factory
    (swept) or an explicit skip reason — nothing falls through."""
    missing = [n for n in NARY
               if n not in FACTORIES and n not in NARY_SKIP]
    assert missing == [], missing


def test_combined_sweep_exceeds_reference_scale():
    """Unary + n-ary verified ops >= 350 (VERDICT r1 item 6 target)."""
    from tests.test_grad_sweep import SWEEP as UNARY
    assert len(UNARY) + len(SWEPT) >= 350, (len(UNARY), len(SWEPT))


def _unpack_factory(name):
    made = FACTORIES[name](_rng(name))
    if len(made) == 3:
        return made
    args, diff_idx = made
    return args, {}, diff_idx


# Ops whose kernels use data-dependent host indexing that check_grads'
# internal vmap cannot trace, or whose max-selection needs controlled
# spacing: verified by direct directional finite differences instead.
MANUAL_FD = {"roi_align", "roi_pool", "psroi_pool", "prroi_pool", "spp"}


@pytest.mark.parametrize("name", SWEPT)
def test_numeric_gradient_nary(name):
    opdef = all_ops()[name]
    args, kwargs, diff_idx = _unpack_factory(name)

    def scalar_fn(*diff_args):
        full = list(args)
        for i, v in zip(diff_idx, diff_args):
            full[i] = jnp.asarray(v)
        out = opdef.fn(*full, **kwargs)
        leaves = [o for o in jax.tree_util.tree_leaves(out)
                  if hasattr(o, "dtype") and
                  jnp.issubdtype(o.dtype, jnp.inexact)]
        if not leaves:
            return None
        return sum(jnp.sum(o) for o in leaves)

    diff_args = tuple(args[i] for i in diff_idx)
    try:
        out0 = scalar_fn(*diff_args)
    except (TypeError, ValueError, NotImplementedError) as e:
        pytest.skip(f"{name}: {e}")
    if out0 is None:
        pytest.skip(f"{name}: no float output")
    if not np.all(np.isfinite(np.asarray(out0))):
        pytest.skip(f"{name}: non-finite at sweep point")
    if name in MANUAL_FD:
        _manual_fd_check(name, scalar_fn, diff_args)
        return
    from jax.test_util import check_grads as jax_check_grads
    jax_check_grads(scalar_fn, diff_args, order=1, modes=("rev",),
                    rtol=2e-2, atol=2e-3, eps=1e-2)


def _manual_fd_check(name, scalar_fn, diff_args, eps=1e-2):
    """Directional central differences vs jax.grad (no vmap)."""
    grads = jax.grad(lambda *a: scalar_fn(*a),
                     argnums=tuple(range(len(diff_args))))(*diff_args)
    rng = np.random.default_rng(zlib.crc32((name + "fd").encode()))
    for trial in range(2):
        vs = [jnp.asarray(rng.normal(size=np.shape(a)).astype(np.float32))
              for a in diff_args]
        plus = scalar_fn(*[a + eps * v for a, v in zip(diff_args, vs)])
        minus = scalar_fn(*[a - eps * v for a, v in zip(diff_args, vs)])
        fd = (float(plus) - float(minus)) / (2 * eps)
        an = float(sum(jnp.vdot(g, v) for g, v in zip(grads, vs)))
        np.testing.assert_allclose(an, fd, rtol=5e-2, atol=5e-3,
                                   err_msg=name)


def test_runtime_skips_stay_rare():
    """Factories that error or go non-finite must not silently erode
    coverage."""
    bad = []
    for name in SWEPT:
        opdef = all_ops()[name]
        try:
            args, diff_idx = FACTORIES[name](_rng(name))
            out = opdef.fn(*args)
            leaves = [o for o in jax.tree_util.tree_leaves(out)
                      if hasattr(o, "dtype") and
                      jnp.issubdtype(o.dtype, jnp.inexact)]
            if leaves and not all(
                    np.all(np.isfinite(np.asarray(o))) for o in leaves):
                bad.append((name, "non-finite"))
        except Exception as e:  # noqa: BLE001 - collecting all failures
            bad.append((name, f"{type(e).__name__}: {str(e)[:60]}"))
    assert len(bad) <= 6, bad
