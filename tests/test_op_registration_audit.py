"""Frozen audit of every reference REGISTER_OPERATOR site.

Reference: the ~700 REGISTER_OPERATOR sites under
paddle/fluid/operators (op_registry.h:278). VERDICT r1 flagged the
registry delta as unaudited; tools/gen_op_audit.py extracts every
registered name and classifies it, and this test freezes the result:
no op may be UNMAPPED, and every claimed mapping must actually resolve
against the live framework (registry op, renamed target, autodiff base,
or importable API component).
"""

import json
import os

import pytest

AUDIT = os.path.join(os.path.dirname(__file__), "..", "tools",
                     "op_registration_audit.json")
VALID_STATUS = {"op", "renamed", "autodiff", "api", "subsumed", "na"}


@pytest.fixture(scope="module")
def audit():
    with open(AUDIT) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def registry():
    import paddle_tpu.dispatch as dispatch
    return set(dispatch.wrapped_ops)


def test_audit_covers_reference_and_nothing_unmapped(audit):
    assert audit["total"] >= 790  # 794 extracted registration names
    assert len(audit["ops"]) == audit["total"]
    unmapped = [n for n, v in audit["ops"].items()
                if v["status"] not in VALID_STATUS]
    assert unmapped == [], unmapped


def test_op_and_renamed_targets_exist(audit, registry):
    bad = []
    for n, v in audit["ops"].items():
        if v["status"] in ("op", "renamed") and \
                v["target"] not in registry:
            bad.append((n, v["target"]))
    assert bad == [], bad


def test_autodiff_bases_are_mapped(audit, registry):
    ops = audit["ops"]
    bad = []
    for n, v in ops.items():
        if v["status"] != "autodiff":
            continue
        base = v["base"]
        if base in ops and ops[base]["status"] in VALID_STATUS:
            continue
        bm = v.get("base_mapping", {})
        if bm.get("status") in VALID_STATUS:
            continue
        if base in registry:
            continue
        bad.append(n)
    assert bad == [], bad


def _resolve(dotted: str) -> bool:
    """Resolve a dotted api target against paddle_tpu."""
    import importlib

    import paddle_tpu
    if dotted.startswith("paddle_tpu."):
        dotted = dotted[len("paddle_tpu."):]
    obj = paddle_tpu
    for part in dotted.split("."):
        try:
            obj = getattr(obj, part)
        except AttributeError:
            try:
                obj = importlib.import_module(
                    f"{obj.__name__}.{part}")
            except Exception:
                return False
    return True


def test_api_targets_resolve(audit):
    bad = []
    for n, v in audit["ops"].items():
        if v["status"] == "api" and not _resolve(v["target"]):
            bad.append((n, v["target"]))
        if v.get("base_mapping", {}).get("status") == "api" and \
                not _resolve(v["base_mapping"]["target"]):
            bad.append((n, v["base_mapping"]["target"]))
    assert bad == [], bad


def test_na_entries_have_reasons(audit):
    for n, v in audit["ops"].items():
        if v["status"] == "na":
            assert v.get("note"), n


def test_new_fallout_ops_work():
    """The real ops the audit surfaced are callable (spot check)."""
    import numpy as np

    from paddle_tpu.ops.detection import (generate_mask_labels,
                                          generate_proposal_labels)

    rois = np.array([[0, 0, 10, 10], [30, 30, 50, 50], [1, 1, 11, 11]],
                    np.float32)
    gts = np.array([[0, 0, 10, 10]], np.float32)
    out_rois, labels, tgt, inside, outside = generate_proposal_labels(
        rois, np.array([5]), gts, batch_size_per_im=4, num_classes=8)
    assert (labels == 5).sum() >= 1  # the matching roi is foreground
    fg0 = int(np.nonzero(labels == 5)[0][0])
    assert inside[fg0, 20:24].all()  # class-5 slot carries the target

    mrois, has_mask, masks = generate_mask_labels(
        60, 60, np.array([5]), [[0.0, 0.0, 10.0, 0.0, 10.0, 10.0,
                                 0.0, 10.0]],
        rois, labels, num_classes=8, resolution=7)
    assert len(mrois) == (labels > 0).sum()
    assert masks.shape[1] == 8 * 7 * 7
    # ExpandMaskTarget: matched class slot binary, all others -1
    per_class = masks.reshape(-1, 8, 49)
    assert per_class[0, 5].max() == 1 and per_class[0, 5].min() >= 0
    others = np.delete(per_class[0], 5, axis=0)
    assert (others == -1).all()
