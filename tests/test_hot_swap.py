"""Weight hot-swap & crash-safe rolling upgrades (r24).

The contracts pinned here (ISSUE r24 acceptance):

- `swap_weights` is validate-then-apply ATOMIC: a structure/shape/
  dtype mismatch, a busy engine, or a same-generation request is a
  typed `SwapFailed` with the old weights still serving and the old
  generation pinned; a valid swap bumps the generation and serves the
  new weights bit-identically to a model built from the same state;
- chain keys are generation-salted at the ROOT only: generation 0 is
  byte-identical to the pre-r24 hash (existing deployments
  unchanged), children inherit the salt through the parent digest,
  and cross-generation lookups miss by construction — a keyed request
  re-issued after a swap serves the NEW weights, never spliced KV;
- the server `swap` op loads + crc-validates the checkpoint on the
  conn thread BEFORE the live engine hears about it: a torn shard is
  a typed `SwapFailed`, the replica keeps serving, and the
  weight_swaps_total{outcome} family + serving_weight_generation
  gauge record exactly what happened;
- `plan_recovery` roll semantics: a half-finished roll resumes
  FORWARD iff the canary proved the checkpoint (a `swapped` record or
  a committed sibling roll to the same generation), otherwise rolls
  BACK to the journal's committed config — and the action stays open
  either way, so a second crash mid-resume resumes again instead of
  stranding a mixed fleet;
- the journal's committed weight config (`record_config`) survives
  adoption, and flight_inspect accepts the `swapped` phase on roll
  actions only;
- a supervisor spawn threads the COMMITTED weight config into the
  replica command line, so monitor respawns and --roles re-role
  restarts never regress to the boot image at generation 0.

Integration (slow lane): chaos INVARIANT 9
(tools/chaos_serving.py --roll-chaos) — SIGKILL the supervisor
mid-roll and a replica mid-swap; one converged generation, typed
termination, zero leaks, clean journal.
"""

import importlib.util
import json
import os
import pathlib
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed.resilience import ResilientCheckpointManager
from paddle_tpu.inference import create_decode_engine
from paddle_tpu.inference.continuous_batching import SwapFailed
from paddle_tpu.models.gpt import (GPTForCausalLM, checkpoint_state,
                                   gpt_tiny, perturbed_state)
from paddle_tpu.serving import ServingMetrics, ServingServer, client_request
from paddle_tpu.serving.autoscaler import (FleetJournal, load_journal,
                                           plan_recovery)
from paddle_tpu.serving.prefix_cache import _block_hash
from paddle_tpu.serving.supervisor import Supervisor

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests."""
    yield


def _fresh_model():
    """A private model per mutating test: swaps apply set_state_dict
    to the instance, so a shared module fixture would leak the
    perturbed weights into later tests."""
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _engine(m, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("num_pages", 12)
    return create_decode_engine(m, **kw)


def _greedy(m, prompt, max_new=6):
    eng = _engine(m)
    rid = eng.submit(np.asarray(prompt, np.int32), max_new)
    out = eng.run()[rid]
    eng.close()
    return [int(t) for t in out[len(prompt):]]


PROMPT = list(range(1, 20))


# ---------------------------------------------------------------------------
# Generation-salted chain keys
# ---------------------------------------------------------------------------

class TestChainKeySalt:
    def test_root_salt_versions_the_whole_chain(self):
        blk = np.arange(8, dtype=np.int32)
        base = _block_hash(None, blk)
        # generation 0 is byte-identical to the pre-r24 hash: boot
        # weights, existing spills and advertisements are unchanged
        assert _block_hash(None, blk, generation=0) == base
        g1 = _block_hash(None, blk, generation=1)
        g2 = _block_hash(None, blk, generation=2)
        assert len({base, g1, g2}) == 3
        # children inherit the salt through the parent digest — and
        # ONLY through it: a non-root hash ignores the generation arg
        child = np.arange(8, 16, dtype=np.int32)
        assert _block_hash(base, child) != _block_hash(g1, child)
        assert _block_hash(g1, child, generation=7) == \
            _block_hash(g1, child)


# ---------------------------------------------------------------------------
# Engine swap_weights: validate-then-apply, typed refusals
# ---------------------------------------------------------------------------

class TestEngineSwap:
    def test_identity_swap_is_bit_identical_and_bumps_generation(self):
        m = _fresh_model()
        eng = _engine(m)
        rid = eng.submit(np.asarray(PROMPT, np.int32), 6)
        before = [int(t) for t in eng.run()[rid][len(PROMPT):]]
        info = eng.swap_weights(checkpoint_state(m))
        assert info["generation"] == 1 and info["leaves"] > 0
        assert info["swap_ms"] >= 0
        assert eng.weight_generation == 1 and eng.weight_swaps == 1
        rid = eng.submit(np.asarray(PROMPT, np.int32), 6)
        after = [int(t) for t in eng.run()[rid][len(PROMPT):]]
        assert after == before
        eng.close()

    def test_perturbed_swap_serves_exactly_the_new_weights(self):
        m = _fresh_model()
        state_b = perturbed_state(checkpoint_state(m), scale=1e-2,
                                  seed=1)
        ref_m = _fresh_model()
        ref_m.set_state_dict(state_b)
        ref = _greedy(ref_m, PROMPT)
        eng = _engine(m)
        eng.swap_weights(state_b, generation=5)
        assert eng.weight_generation == 5
        rid = eng.submit(np.asarray(PROMPT, np.int32), 6)
        got = [int(t) for t in eng.run()[rid][len(PROMPT):]]
        assert got == ref
        eng.close()

    def test_structure_and_shape_mismatch_refused_typed(self):
        m = _fresh_model()
        eng = _engine(m)
        rid = eng.submit(np.asarray(PROMPT, np.int32), 4)
        before = [int(t) for t in eng.run()[rid][len(PROMPT):]]
        good = checkpoint_state(m)
        missing = dict(good)
        dropped = sorted(missing)[0]
        del missing[dropped]
        with pytest.raises(SwapFailed, match="structure mismatch"):
            eng.swap_weights(missing)
        extra = dict(good)
        extra["not_a_real_leaf"] = np.zeros(3, np.float32)
        with pytest.raises(SwapFailed, match="structure mismatch"):
            eng.swap_weights(extra)
        torn = dict(good)
        name = sorted(torn)[0]
        leaf = np.asarray(getattr(torn[name], "value", torn[name]))
        torn[name] = np.zeros(tuple(s + 1 for s in leaf.shape),
                              leaf.dtype)
        with pytest.raises(SwapFailed, match="tree mismatch"):
            eng.swap_weights(torn)
        # all-or-nothing: nothing was touched, old weights serve, the
        # generation never moved
        assert eng.weight_generation == 0 and eng.weight_swaps == 0
        rid = eng.submit(np.asarray(PROMPT, np.int32), 4)
        assert [int(t)
                for t in eng.run()[rid][len(PROMPT):]] == before
        eng.close()

    def test_same_generation_and_busy_engine_refused(self):
        m = _fresh_model()
        eng = _engine(m)
        with pytest.raises(SwapFailed, match="already serving"):
            eng.swap_weights(checkpoint_state(m), generation=0)
        eng.submit(np.asarray(PROMPT, np.int32), 4)
        eng.step()  # admits: an active slot pins the old weights
        assert eng.num_active > 0
        with pytest.raises(SwapFailed, match="busy"):
            eng.swap_weights(checkpoint_state(m))
        eng.run()  # in-flight work finishes on the old weights
        eng.close()


# ---------------------------------------------------------------------------
# Server swap op: conn-thread validation, keyed no-cross-splice
# ---------------------------------------------------------------------------

class TestServerSwapOp:
    def _serve(self, m, **kw):
        kw.setdefault("num_slots", 2)
        kw.setdefault("page_size", 8)
        kw.setdefault("max_seq_len", 96)
        kw.setdefault("num_pages", 12)
        kw.setdefault("metrics",
                      ServingMetrics(registry=StatRegistry()))
        return ServingServer(m, **kw)

    def test_swap_end_to_end_keyed_reissue_serves_new_weights(
            self, tmp_path):
        m = _fresh_model()
        state_b = perturbed_state(checkpoint_state(m), scale=1e-2,
                                  seed=2)
        ref_m = _fresh_model()
        ref_m.set_state_dict(state_b)
        ref = _greedy(ref_m, PROMPT)
        ResilientCheckpointManager(str(tmp_path / "ck")).save(
            1, state_b)
        srv = self._serve(m)
        port = srv.start()
        try:
            req = {"op": "generate", "prompt": PROMPT,
                   "max_new_tokens": 6, "key": "swap-k0"}
            r0 = client_request("127.0.0.1", port, dict(req))
            assert "error" not in r0, r0
            rep = client_request("127.0.0.1", port,
                                 {"op": "swap",
                                  "checkpoint": str(tmp_path / "ck"),
                                  "generation": 1})
            assert rep.get("generation") == 1, rep
            assert rep.get("swap_ms", -1) >= 0
            st = client_request("127.0.0.1", port, {"op": "stats"})
            assert st["weight_generation"] == 1
            assert st["weight_swaps"] == 1
            # the SAME key after the swap: generation-salted chain
            # keys make the old cached prefix miss by construction —
            # the reply is the new weights' reference, never a
            # hybrid spliced from old-generation KV
            r1 = client_request("127.0.0.1", port, dict(req))
            assert r1.get("generated") == ref, r1
            mx = client_request("127.0.0.1", port, {"op": "metrics"})
            assert "serving_weight_generation 1" in mx["text"]
            assert 'weight_swaps_total{outcome="committed"} 1' \
                in mx["text"]
        finally:
            srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_corrupt_checkpoint_refused_old_weights_keep_serving(
            self, tmp_path):
        m = _fresh_model()
        ck = tmp_path / "ck-bad"
        ResilientCheckpointManager(str(ck)).save(
            1, perturbed_state(checkpoint_state(m), seed=3))
        step_dir = ck / "step_00000001"
        shard = sorted(f for f in os.listdir(step_dir)
                       if f.endswith(".npy"))[0]
        with open(step_dir / shard, "r+b") as f:
            f.seek(os.path.getsize(step_dir / shard) // 2)
            f.write(b"\xff" * 16)
        srv = self._serve(m)
        port = srv.start()
        try:
            req = {"op": "generate", "prompt": PROMPT,
                   "max_new_tokens": 6}
            before = client_request("127.0.0.1", port, dict(req))
            rep = client_request("127.0.0.1", port,
                                 {"op": "swap",
                                  "checkpoint": str(ck)})
            assert rep.get("error") == "SwapFailed", rep
            assert "no valid checkpoint" in rep["reason"]
            # a missing directory and a bad request are typed too
            rep = client_request(
                "127.0.0.1", port,
                {"op": "swap",
                 "checkpoint": str(tmp_path / "nope")})
            assert rep.get("error") == "SwapFailed", rep
            assert client_request(
                "127.0.0.1", port,
                {"op": "swap"}).get("error") == "BadRequest"
            st = client_request("127.0.0.1", port, {"op": "stats"})
            assert st["weight_generation"] == 0
            after = client_request("127.0.0.1", port, dict(req))
            assert after["generated"] == before["generated"]
            assert st["stats"]["counters"][
                "weight_swaps_failed_total"] >= 2
        finally:
            srv.stop()
        srv.engine.allocator.check_no_leak()


# ---------------------------------------------------------------------------
# plan_recovery: roll resume direction (pure)
# ---------------------------------------------------------------------------

def _body(fleet=(), actions=(), config=None, seq=None):
    seqs = [a["seq"] for a in actions] or [0]
    body = {"seq": seq if seq is not None else max(seqs),
            "supervisor_pid": 12345,
            "fleet": list(fleet), "actions": list(actions)}
    if config is not None:
        body["config"] = dict(config)
    return body


def _roll_begin(seq, replica=1, gen_to=3, **extra):
    e = {"seq": seq, "action": "roll", "phase": "begin",
         "replica": replica, "checkpoint": "/ck/new",
         "generation_from": 0, "generation_to": gen_to,
         "pid": 300, "port": 8900, "role": "mixed"}
    e.update(extra)
    return e


_FLEET = [{"idx": 0, "pid": 100, "port": 8800, "role": "mixed"},
          {"idx": 1, "pid": 300, "port": 8900, "role": "mixed"}]
_CFG = {"checkpoint": "/ck/old", "generation": 1}


class TestPlanRecoveryRoll:
    def test_unproven_roll_resumes_backward_to_committed_config(self):
        body = _body(fleet=_FLEET, actions=[_roll_begin(7)],
                     config=_CFG)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        (res,) = plan["resume"]
        assert res["action"] == "roll_back" and res["seq"] == 7
        # the direction's target is the JOURNAL's committed config,
        # not the half-applied roll's
        assert res["checkpoint"] == "/ck/old"
        assert res["generation"] == 1
        # the action stays OPEN: a second crash mid-resume resumes
        # again — the journal never forgets a half-rolled fleet
        assert all(seq != 7 for seq, _, _ in plan["resolve"])
        # the victim is a normal member again (adopted while live)
        assert any(e["idx"] == 1 for e in plan["adopt"])

    def test_swapped_record_resumes_forward(self):
        body = _body(fleet=_FLEET,
                     actions=[_roll_begin(7),
                              {"seq": 7, "phase": "swapped",
                               "swapped": True}],
                     config=_CFG)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        (res,) = plan["resume"]
        assert res["action"] == "roll" and res["generation"] == 3
        assert res["checkpoint"] == "/ck/new"
        assert all(seq != 7 for seq, _, _ in plan["resolve"])

    def test_committed_sibling_roll_proves_generation_forward(self):
        # the canary's roll to generation 3 committed; replica 1's is
        # open and unswapped — the checkpoint is PROVEN, converge
        # forward instead of swapping the canary back
        acts = [_roll_begin(6, replica=0),
                {"seq": 6, "phase": "commit"},
                _roll_begin(7, replica=1)]
        body = _body(fleet=_FLEET, actions=acts, config=_CFG)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        (res,) = plan["resume"]
        assert res["action"] == "roll" and res["generation"] == 3

    def test_committed_rollback_sibling_proves_nothing(self):
        # a committed ROLLBACK-marked roll to generation 3 is the
        # auto-rollback sweep, not proof the new weights work
        acts = [_roll_begin(6, replica=0, rollback=True),
                {"seq": 6, "phase": "commit"},
                _roll_begin(7, replica=1)]
        body = _body(fleet=_FLEET, actions=acts, config=_CFG)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: True)
        (res,) = plan["resume"]
        assert res["action"] == "roll_back"

    def test_dead_roll_victim_respawned_not_stranded(self):
        body = _body(fleet=_FLEET, actions=[_roll_begin(7)],
                     config=_CFG)
        plan = plan_recovery(body, {}, 1, 4,
                             alive=lambda pid, port: pid == 100)
        assert {"idx": 1, "role": "mixed"} in plan["respawn"]
        assert plan["resume"][0]["action"] == "roll_back"


# ---------------------------------------------------------------------------
# Journal committed config + flight_inspect roll phases
# ---------------------------------------------------------------------------

class TestJournalConfigAndLint:
    def test_record_config_roundtrip_and_adoption(self, tmp_path):
        path = str(tmp_path / "j.json")
        j = FleetJournal(path)
        assert j.config() == {}
        j.record_config("/ck/rolled", 4)
        body, err = load_journal(path)
        assert err is None
        assert body["config"] == {"checkpoint": "/ck/rolled",
                                  "generation": 4}
        j2 = FleetJournal(path)  # the restarted supervisor
        j2.adopt_body(body)
        assert j2.config()["generation"] == 4
        s = j2.begin("spawn", replica=0)
        j2.commit(s)
        body, _ = load_journal(path)  # config survives later writes
        assert body["config"]["checkpoint"] == "/ck/rolled"

    def test_swapped_phase_legal_on_roll_actions_only(self, tmp_path):
        fin = _load_tool("flight_inspect")
        path = str(tmp_path / "j.json")
        j = FleetJournal(path)
        seq = j.begin("roll", replica=0, checkpoint="/ck/new",
                      generation_from=0, generation_to=1)
        j.update(seq, phase="swapped", swapped=True)
        j.commit(seq)
        obj = json.loads(open(path).read())
        assert fin.lint_fleet_journal(obj, allow_open_tail=0) == []
        s2 = j.begin("spawn", replica=1, role="mixed")
        j.update(s2, phase="swapped", swapped=True)
        j.commit(s2)
        obj = json.loads(open(path).read())
        errs = fin.lint_fleet_journal(obj, allow_open_tail=0)
        assert errs and any("roll" in e for e in errs)


# ---------------------------------------------------------------------------
# Supervisor: committed weight config threads into every spawn
# ---------------------------------------------------------------------------

class TestSupervisorWeightConfig:
    def test_spawn_carries_committed_checkpoint_and_generation(
            self, tmp_path, monkeypatch):
        from paddle_tpu.serving import supervisor as sup_mod
        sup = Supervisor(model="gpt_tiny", replicas=1,
                         collect_metrics=False, log_dir=str(tmp_path),
                         checkpoint="/ck/rolled", weight_generation=4)
        captured = {}

        class _FakeProc:
            pid = 4242

            def poll(self):
                return None

        monkeypatch.setattr(
            sup_mod.subprocess, "Popen",
            lambda cmd, **kw: captured.setdefault("cmd", cmd)
            and _FakeProc() or _FakeProc())
        rep = sup.replicas[0]
        sup._spawn(rep)
        rep.close_log()
        cmd = captured["cmd"]
        assert cmd[cmd.index("--checkpoint") + 1] == "/ck/rolled"
        assert cmd[cmd.index("--weight-generation") + 1] == "4"

    def test_roll_fleet_refuses_without_live_replicas(self, tmp_path):
        sup = Supervisor(model="gpt_tiny", replicas=1,
                         collect_metrics=False, log_dir=str(tmp_path))
        out = sup.roll_fleet("/ck/new")
        assert out == {"ok": False, "refused": "no_live_replica"}


# ---------------------------------------------------------------------------
# Integration (slow lane): chaos INVARIANT 9
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_invariant9_roll_chaos():
    chaos = _load_tool("chaos_serving")
    report = chaos.run_roll_chaos(requests=6)
    assert report.ok, json.dumps(report.to_dict(), indent=2)
