"""Long-tail math/fft/nn-functional op tests vs NumPy references.

Mirrors the reference's per-op unit tests for the extended surface
(test_frexp_op, test_lu_unpack_op, test_fold_op, test_fft, ...)."""

import numpy as np
import pytest

from op_test import check_forward, check_grad

pytestmark = pytest.mark.slow  # covered breadth; fast lane keeps sibling smokes

RNG = np.random.default_rng(7)


def _f32(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


def test_frexp_ldexp():
    x = _f32(3, 4) * 10
    check_forward("frexp", np.frexp, x)
    m, e = np.frexp(x)
    check_forward("ldexp", lambda a, b: np.ldexp(a, b), m,
                  e.astype(np.int32))


def test_renorm():
    import paddle_tpu as pt
    x = _f32(4, 5)
    out = pt.dispatch.wrap_op("renorm")(pt.to_tensor(x), 2.0, 0, 1.0)
    norms = np.linalg.norm(np.asarray(out.value), axis=1)
    assert (norms <= 1.0 + 1e-5).all()
    # rows already under the cap are untouched
    small = x / (np.linalg.norm(x, axis=1, keepdims=True) + 1e-9) * 0.5
    out2 = pt.dispatch.wrap_op("renorm")(pt.to_tensor(small), 2.0, 0, 1.0)
    np.testing.assert_allclose(np.asarray(out2.value), small, rtol=1e-5)


def test_trapezoid_family():
    y, x = np.abs(_f32(3, 8)) + 0.1, np.sort(_f32(8))
    check_forward("trapezoid", lambda yy, xx: np.trapezoid(yy, x=xx), y, x)
    from scipy.integrate import cumulative_trapezoid as ref_ct
    check_forward("cumulative_trapezoid",
                  lambda yy, xx: ref_ct(yy, x=xx, axis=-1), y, x)
    check_grad("trapezoid", y, x, arg_idx=(0,))


def test_vander_cartesian_combinations():
    x = _f32(5)
    check_forward("vander", lambda v: np.vander(v, increasing=False), x)
    import paddle_tpu as pt
    a = np.array([1.0, 2.0], np.float32)
    b = np.array([3.0, 4.0, 5.0], np.float32)
    out = pt.dispatch.wrap_op("cartesian_prod")(
        [pt.to_tensor(a), pt.to_tensor(b)])
    assert np.asarray(out.value).shape == (6, 2)
    comb = pt.dispatch.wrap_op("combinations")(pt.to_tensor(x), 2)
    import itertools
    exp = np.array(list(itertools.combinations(x, 2)), np.float32)
    np.testing.assert_allclose(np.asarray(comb.value), exp, rtol=1e-6)


def test_index_fill_masked_scatter_diag_embed():
    import paddle_tpu as pt
    x = _f32(3, 4)
    idx = np.array([0, 2], np.int32)
    out = pt.dispatch.wrap_op("index_fill")(
        pt.to_tensor(x), pt.to_tensor(idx), 0, -1.0)
    got = np.asarray(out.value)
    assert (got[[0, 2]] == -1.0).all() and (got[1] == x[1]).all()

    mask = x > 0
    vals = np.arange(mask.sum() + 2, dtype=np.float32)
    out = pt.dispatch.wrap_op("masked_scatter")(
        pt.to_tensor(x), pt.to_tensor(mask), pt.to_tensor(vals))
    got = np.asarray(out.value)
    np.testing.assert_allclose(got[mask], vals[:mask.sum()])
    np.testing.assert_allclose(got[~mask], x[~mask])

    v = _f32(2, 3)
    out = pt.dispatch.wrap_op("diag_embed")(pt.to_tensor(v))
    got = np.asarray(out.value)
    assert got.shape == (2, 3, 3)
    for i in range(2):
        np.testing.assert_allclose(got[i], np.diag(v[i]), rtol=1e-6)
    out = pt.dispatch.wrap_op("diag_embed")(pt.to_tensor(v), 1)
    assert np.asarray(out.value).shape == (2, 4, 4)


def test_views_and_strides():
    import paddle_tpu as pt
    x = _f32(2, 12)
    out = pt.dispatch.wrap_op("unflatten")(pt.to_tensor(x), 1, (3, 4))
    assert np.asarray(out.value).shape == (2, 3, 4)
    other = np.zeros((4, 6), np.float32)
    out = pt.dispatch.wrap_op("view_as")(pt.to_tensor(x),
                                         pt.to_tensor(other))
    assert np.asarray(out.value).shape == (4, 6)
    base = np.arange(12, dtype=np.float32)
    got = pt.dispatch.wrap_op("as_strided")(pt.to_tensor(base),
                                            (3, 4), (1, 3))
    exp = np.lib.stride_tricks.as_strided(base, (3, 4), (4, 12))
    np.testing.assert_allclose(np.asarray(got.value), exp)


def test_bincount():
    import paddle_tpu as pt
    x = np.array([1, 1, 3, 0, 3, 3], np.int32)
    got = pt.dispatch.wrap_op("bincount")(pt.to_tensor(x))
    np.testing.assert_array_equal(np.asarray(got.value), np.bincount(x))
    w = _f32(6)
    got = pt.dispatch.wrap_op("bincount")(pt.to_tensor(x),
                                          pt.to_tensor(w), 6)
    np.testing.assert_allclose(np.asarray(got.value),
                               np.bincount(x, w, 6), rtol=1e-6)


def test_lu_unpack_reconstructs():
    import paddle_tpu as pt
    a = _f32(5, 5) + 5 * np.eye(5, dtype=np.float32)
    lu_t, piv = pt.dispatch.wrap_op("lu")(pt.to_tensor(a))
    P, L, U = pt.dispatch.wrap_op("lu_unpack")(lu_t, piv)
    rec = np.asarray(P.value) @ np.asarray(L.value) @ np.asarray(U.value)
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)


def test_cdist_pairwise_distance():
    x, y = _f32(4, 3), _f32(5, 3)
    from scipy.spatial.distance import cdist as ref_cdist
    check_forward("cdist", lambda a, b: ref_cdist(a, b, "euclidean"),
                  x, y, rtol=1e-4, atol=1e-5)
    check_forward(
        "pairwise_distance",
        lambda a, b: np.linalg.norm(np.abs(a - b) + 1e-6, axis=-1),
        x, _f32(4, 3), rtol=1e-5, atol=1e-6)


def test_complex_polar():
    re, im = _f32(3), _f32(3)
    check_forward("complex", lambda a, b: a + 1j * b, re, im)
    r = np.abs(_f32(3)) + 0.1
    th = _f32(3)
    check_forward("polar", lambda a, t: a * np.exp(1j * t), r, th,
                  rtol=1e-5, atol=1e-6)


FFT_CASES = [
    ("fft", np.fft.fft), ("ifft", np.fft.ifft), ("rfft", np.fft.rfft),
    ("fftshift", np.fft.fftshift),
]


@pytest.mark.parametrize("name,ref", FFT_CASES,
                         ids=[c[0] for c in FFT_CASES])
def test_fft_basic(name, ref):
    x = _f32(4, 8)
    check_forward(name, ref, x, rtol=1e-4, atol=1e-4)


def test_fft_roundtrip_and_2d():
    import paddle_tpu as pt
    x = _f32(4, 8)
    X = pt.dispatch.wrap_op("rfft")(pt.to_tensor(x))
    back = pt.dispatch.wrap_op("irfft")(X)
    np.testing.assert_allclose(np.asarray(back.value), x, atol=1e-5)
    X2 = pt.dispatch.wrap_op("fft2")(pt.to_tensor(x))
    np.testing.assert_allclose(np.asarray(X2.value), np.fft.fft2(x),
                               rtol=1e-4, atol=1e-4)
    f = pt.dispatch.wrap_op("fftfreq")(8, 0.5)
    np.testing.assert_allclose(np.asarray(f.value), np.fft.fftfreq(8, 0.5))


def test_fold_inverts_unfold():
    import paddle_tpu as pt
    x = _f32(2, 3, 8, 8)
    cols = pt.dispatch.wrap_op("unfold")(pt.to_tensor(x), 2, 2, 0)
    back = pt.dispatch.wrap_op("fold")(cols, (8, 8), 2, 2, 0)
    # non-overlapping stride == kernel: fold(unfold(x)) == x
    np.testing.assert_allclose(np.asarray(back.value), x, rtol=1e-6)
    # overlapping windows sum: ones stay countable
    ones = np.ones((1, 1, 4, 4), np.float32)
    cols = pt.dispatch.wrap_op("unfold")(pt.to_tensor(ones), 3, 1, 0)
    back = pt.dispatch.wrap_op("fold")(cols, (4, 4), 3, 1, 0)
    assert np.asarray(back.value).max() == 4.0  # center overlaps 4 windows


def test_lp_pool_thresholded_relu():
    import paddle_tpu as pt
    x = np.abs(_f32(1, 1, 4, 4)) + 0.1
    out = pt.dispatch.wrap_op("lp_pool2d")(pt.to_tensor(x), 2.0, 2, 2)
    exp = np.zeros((1, 1, 2, 2), np.float32)
    for i in range(2):
        for j in range(2):
            win = x[0, 0, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
            exp[0, 0, i, j] = np.sqrt((win ** 2).sum())
    np.testing.assert_allclose(np.asarray(out.value), exp, rtol=1e-5)
    check_forward("thresholded_relu", lambda v, threshold:
                  np.where(v > threshold, v, 0.0).astype(v.dtype),
                  _f32(3, 4), threshold=0.5)


def test_pad3d_zeropad2d():
    import paddle_tpu as pt
    x = _f32(1, 2, 3, 4, 5)
    out = pt.dispatch.wrap_op("pad3d")(pt.to_tensor(x),
                                       [1, 1, 2, 2, 0, 1])
    assert np.asarray(out.value).shape == (1, 2, 4, 8, 7)
    y = _f32(1, 2, 3, 4)
    out = pt.dispatch.wrap_op("zeropad2d")(pt.to_tensor(y), [1, 2, 3, 4])
    got = np.asarray(out.value)
    assert got.shape == (1, 2, 10, 7)
    np.testing.assert_allclose(got[:, :, 3:6, 1:5], y)


def test_tail_losses():
    x, y01 = _f32(4, 5), (RNG.random((4, 5)) > 0.5).astype(np.float32)
    ysign = np.sign(_f32(4, 5)) + (np.sign(_f32(4, 5)) == 0)

    def ref_soft_margin(inp, lab):
        return np.log1p(np.exp(-lab * inp)).mean()

    check_forward("soft_margin_loss", ref_soft_margin, x,
                  ysign.astype(np.float32), rtol=1e-5, atol=1e-6)
    check_grad("soft_margin_loss", x, ysign.astype(np.float32),
               arg_idx=(0,))

    def ref_mlsm(inp, lab):
        sig = 1.0 / (1.0 + np.exp(-inp))
        per = -(lab * np.log(sig) + (1 - lab) * np.log(1 - sig))
        return per.mean(axis=-1).mean()

    check_forward("multi_label_soft_margin_loss", ref_mlsm, x, y01,
                  rtol=1e-4, atol=1e-5)

    lam = np.abs(_f32(4, 5)) + 0.5

    def ref_poisson(inp, lab):
        return (np.exp(inp) - lab * inp).mean()

    check_forward("poisson_nll_loss", ref_poisson, x, lam,
                  rtol=1e-4, atol=1e-5)

    var = np.abs(_f32(4, 5)) + 0.1

    def ref_gauss(inp, lab, variance):
        return (0.5 * (np.log(variance) +
                       (inp - lab) ** 2 / variance)).mean()

    check_forward("gaussian_nll_loss", ref_gauss, x, lam, var,
                  rtol=1e-4, atol=1e-5)


def test_random_tail():
    import paddle_tpu as pt
    pt.seed(0)
    s = pt.dispatch.wrap_op("binomial")(
        np.full((20000,), 10.0, np.float32), np.full((20000,), 0.3,
                                                     np.float32))
    m = float(np.asarray(s.value).mean())
    assert abs(m - 3.0) < 0.1
    ln = pt.dispatch.wrap_op("lognormal")(0.0, 0.5, (20000,))
    got = np.log(np.asarray(ln.value))
    assert abs(got.mean()) < 0.05 and abs(got.std() - 0.5) < 0.05
    g = pt.dispatch.wrap_op("standard_gamma")(
        np.full((20000,), 2.0, np.float32))
    assert abs(float(np.asarray(g.value).mean()) - 2.0) < 0.1


def test_nan_quantile_median():
    x = _f32(4, 6)
    x[1, 2] = np.nan
    check_forward("nanmedian", lambda v: np.nanmedian(v), x)
    check_forward("nanquantile", lambda v, q: np.nanquantile(v, q),
                  x, 0.25, rtol=1e-5, atol=1e-6)


def test_tensor_api_tail():
    import paddle_tpu as pt
    W = pt.dispatch.wrap_op

    x = _f32(3, 4)
    np.testing.assert_allclose(
        np.asarray(W("take")(pt.to_tensor(x),
                             pt.to_tensor(np.array([0, 5, 11]))).value),
        x.ravel()[[0, 5, 11]])
    p = np.clip(np.abs(_f32(5)), 0.05, 0.95)
    check_forward("logit", lambda v: np.log(v / (1 - v)), p,
                  rtol=1e-5, atol=1e-6)
    from scipy import special as sp
    check_forward("i0", sp.i0, _f32(4), rtol=1e-4, atol=1e-5)
    check_forward("i1", sp.i1, _f32(4), rtol=1e-4, atol=1e-5)
    bins = np.array([0.0, 1.0, 2.0], np.float32)
    check_forward("digitize", np.digitize, _f32(6) + 1.0, bins)
    a, b = _f32(2, 3, 4), _f32(4, 3, 5)
    check_forward("tensordot",
                  lambda u, v, axes: np.tensordot(u, v, axes=axes),
                  a, b, axes=[[2, 1], [0, 1]], rtol=1e-4, atol=1e-5)
    parts = W("tensor_split")(pt.to_tensor(_f32(7, 2)), 3)
    assert [np.asarray(q.value).shape[0] for q in parts] == [3, 2, 2]
    bd = W("block_diag")([pt.to_tensor(_f32(2, 2)),
                          pt.to_tensor(_f32(3, 1))])
    assert np.asarray(bd.value).shape == (5, 3)
    check_forward("addcmul", lambda v, t1, t2, value: v + value * t1 * t2,
                  _f32(3), _f32(3), _f32(3), value=0.5)
    check_forward("bitwise_left_shift", np.left_shift,
                  np.array([1, 2, 4], np.int32), np.array([1, 2, 3],
                                                          np.int32))
    assert W("is_floating_point")(pt.to_tensor(x))
    assert not W("is_complex")(pt.to_tensor(x))
    assert W("rank")(pt.to_tensor(x)) == 2
