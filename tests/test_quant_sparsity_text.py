"""Quantization (QAT/PTQ), 2:4 sparsity, text datasets."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as optim

pytestmark = pytest.mark.slow  # covered breadth; fast lane keeps sibling smokes


def test_fake_quant_ste_grads():
    from paddle_tpu.quantization import fake_quant

    x = pt.to_tensor(np.linspace(-1, 1, 16, dtype=np.float32),
                     stop_gradient=False)
    y = fake_quant(x, pt.to_tensor(np.float32(1.0)))
    # quantized values are on the int8 grid
    q = y.numpy() * 127
    np.testing.assert_allclose(q, np.round(q), atol=1e-4)
    # straight-through: grad is identity
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones(16), rtol=1e-6)


def test_qat_quantize_and_train():
    from paddle_tpu.quantization import ImperativeQuantAware, QuantizedLinear

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    qat = ImperativeQuantAware()
    qat.quantize(net)
    assert isinstance(net._sub_layers["0"], QuantizedLinear)
    opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
    x = pt.randn((4, 8))
    y = pt.randn((4, 4))
    mse = nn.MSELoss()
    losses = []
    for _ in range(10):
        loss = mse(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_ptq_calibration_and_export():
    from paddle_tpu.quantization import PTQ

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ptq = PTQ()
    data = [(pt.randn((4, 8)),) for _ in range(4)]
    ptq.calibrate(net, data, num_batches=4)
    qw = ptq.quantize_weights(net)
    assert len(qw) == 2
    for name, rec in qw.items():
        assert rec["weight_int8"].dtype == np.int8
        assert rec["act_scale"] is not None and rec["act_scale"] > 0
        # dequantized weight approximates the original
        w = dict(net.named_parameters())[name + ".weight"].numpy()
        scale = rec["weight_scale"]
        deq = rec["weight_int8"].astype(np.float32) / 127.0
        if scale.ndim:  # per-channel on some axis
            if rec["weight_int8"].shape[0] == scale.shape[0]:
                deq = deq * scale[:, None]
            else:
                deq = deq * scale[None, :]
        else:
            deq = deq * scale
        assert np.abs(deq - w).max() < np.abs(w).max() * 0.05 + 1e-3


def test_sparsity_2_4():
    from paddle_tpu import sparsity

    net = nn.Linear(16, 8)
    masks = sparsity.prune_model(net)
    assert "weight" in masks
    assert sparsity.check_sparsity(net.weight.numpy())
    # decorated optimizer keeps the mask after updates
    opt = sparsity.decorate(optim.SGD(learning_rate=0.1,
                                      parameters=net.parameters()))
    x = pt.randn((4, 16))
    net(x).sum().backward()
    opt.step()
    assert sparsity.check_sparsity(net.weight.numpy())
    sparsity.reset_masks()


def test_text_vocab_and_imdb():
    from paddle_tpu.text import Imdb, Vocab

    ds = Imdb(mode="train", synthetic_size=64)
    ids, label = ds[0]
    assert ids.shape == (32,)
    assert label in (0, 1)
    v = ds.vocab
    enc = v.encode(["great", "zzzunknown"])
    assert enc[1] == v.unk_id
    assert v.decode(enc)[0] == "great"


def test_text_classifier_trains():
    from paddle_tpu.io import DataLoader
    from paddle_tpu.text import Imdb

    pt.seed(123)
    ds = Imdb(mode="train", synthetic_size=128)
    loader = DataLoader(ds, batch_size=32, shuffle=True)

    class Clf(nn.Layer):
        def __init__(self, vocab):
            super().__init__()
            self.emb = nn.Embedding(vocab, 16)
            self.fc = nn.Linear(16, 2)

        def forward(self, ids):
            return self.fc(pt.mean(self.emb(ids), axis=1))

    model = Clf(len(ds.vocab))
    opt = optim.Adam(learning_rate=0.01, parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    losses = []
    for _ in range(8):
        for ids, label in loader:
            loss = ce(model(ids), label)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
    assert np.mean(losses[-4:]) < np.mean(losses[:4]) * 0.9, (
        losses[:4], losses[-4:])


def test_synthetic_lm_dataset():
    from paddle_tpu.text import SyntheticLMDataset

    ds = SyntheticLMDataset(vocab_size=64, seq_len=16, size=8)
    x, y = ds[0]
    assert x.shape == (16,) and y.shape == (16,)
    np.testing.assert_array_equal(x[1:], y[:-1])
    x2, _ = ds[0]
    np.testing.assert_array_equal(x, x2)  # deterministic


def test_viterbi_decode():
    from paddle_tpu.text import viterbi_decode

    # 2 states, clear best path
    pot = np.array([[[2.0, 0.0], [0.0, 2.0], [2.0, 0.0]]], np.float32)
    trans = np.zeros((2, 2), np.float32)
    scores, paths = viterbi_decode(pot, trans)
    np.testing.assert_array_equal(np.asarray(paths)[0], [0, 1, 0])
    np.testing.assert_allclose(np.asarray(scores)[0], 6.0)


def test_weight_only_int8_decode_path():
    """convert_to_weight_only_int8: swaps Linear + tensor-parallel
    linears in place, outputs track the fp model closely (weight-only
    — no activation quantization error), and generate() still runs
    end to end on the converted model."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny
    from paddle_tpu.quantization.quant import (WeightOnlyInt8Linear,
                                               convert_to_weight_only_int8)

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    ids = pt.Tensor(jnp.asarray(
        np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % 1000))
    ref_logits = model(ids)
    n = convert_to_weight_only_int8(model)
    assert n >= 2 * 4, n  # qkv/out/fc_in/fc_out per block (tied lm head)
    got_logits = model(ids)
    r = np.asarray(ref_logits.value)
    g = np.asarray(got_logits.value)
    # weight-only int8 at per-channel scales: small relative drift
    assert np.max(np.abs(r - g)) / (np.abs(r).max() + 1e-9) < 0.05
    # argmax token agreement on most positions (decode fidelity)
    agree = (r.argmax(-1) == g.argmax(-1)).mean()
    assert agree > 0.9, agree
    # kv-cache decode still runs through the swapped layers
    out = model.generate(pt.Tensor(ids.value[:, :8]), max_new_tokens=4,
                         temperature=0.0, use_jit=True)
    v = out.value if hasattr(out, "value") else out
    assert v.shape[1] == 12
    # the swap is the documented type, holding int8 buffers
    lin = model.gpt.h[0].mlp.fc_in
    assert isinstance(lin, WeightOnlyInt8Linear)
    assert np.asarray(lin.weight_int8.value).dtype == np.int8
