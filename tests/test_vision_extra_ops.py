"""Vision/conv/CTR extra-op tests vs NumPy references.

Mirrors reference unit tests: test_affine_channel_op.py,
test_space_to_depth_op.py, test_row_conv_op.py, test_conv_shift_op.py,
test_bilinear_tensor_product_op.py, test_fsp_op.py, test_im2sequence_op.py,
test_partial_concat_op.py, test_unpool_op.py, test_spp_op.py,
test_psroi_pool_op.py, test_prroi_pool_op.py, test_deformable_conv_op.py,
test_yolov3_loss_op.py, test_cvm_op.py, test_batch_fc_op.py under
python/paddle/fluid/tests/unittests/.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import nn_functional as NF
from paddle_tpu.ops import vision_extra as V

pytestmark = pytest.mark.slow  # covered breadth; fast lane keeps sibling smokes

RNG = np.random.default_rng(3)


def _f32(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


def test_affine_channel():
    x = _f32(2, 3, 4, 4)
    s, b = _f32(3), _f32(3)
    got = V.affine_channel(jnp.asarray(x), jnp.asarray(s), jnp.asarray(b))
    np.testing.assert_allclose(
        np.asarray(got), x * s[None, :, None, None] + b[None, :, None, None],
        rtol=1e-6)
    x2 = _f32(5, 3)
    got2 = V.affine_channel(jnp.asarray(x2), jnp.asarray(s), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(got2), x2 * s + b, rtol=1e-6)


def test_space_to_depth_roundtrip():
    x = _f32(2, 4, 6, 6)
    y = V.space_to_depth(jnp.asarray(x), 2)
    assert y.shape == (2, 16, 3, 3)
    # inverse via pixel_shuffle-style reshape
    z = np.asarray(y).reshape(2, 2, 2, 4, 3, 3).transpose(
        0, 3, 4, 1, 5, 2).reshape(2, 4, 6, 6)
    np.testing.assert_allclose(z, x)


def test_shuffle_channel_involution():
    x = _f32(2, 6, 3, 3)
    y = V.shuffle_channel(jnp.asarray(x), 2)
    z = V.shuffle_channel(y, 3)  # shuffling by c//g inverts
    np.testing.assert_allclose(np.asarray(z), x)


def test_cvm():
    x = np.abs(_f32(4, 6)) + 1.0
    y = V.cvm(jnp.asarray(x), None, use_cvm=True)
    np.testing.assert_allclose(np.asarray(y)[:, 0], np.log(x[:, 0] + 1),
                               rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(y)[:, 1], np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(y)[:, 2:], x[:, 2:])
    y2 = V.cvm(jnp.asarray(x), None, use_cvm=False)
    np.testing.assert_allclose(np.asarray(y2), x[:, 2:])


def test_row_conv():
    x = _f32(2, 5, 3)
    w = _f32(3, 3)  # context 3
    got = np.asarray(V.row_conv(jnp.asarray(x), jnp.asarray(w)))
    ref = np.zeros_like(x)
    for t in range(5):
        for j in range(3):
            if t + j < 5:
                ref[:, t] += x[:, t + j] * w[j]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_conv_shift():
    x = _f32(2, 7)
    y = _f32(2, 3)
    got = np.asarray(V.conv_shift(jnp.asarray(x), jnp.asarray(y)))
    ref = np.zeros_like(x)
    for i in range(2):
        for j in range(7):
            for k in range(3):
                ref[i, j] += x[i, (j - 1 + k) % 7] * y[i, k]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_bilinear_tensor_product():
    x, y = _f32(4, 3), _f32(4, 5)
    w = _f32(6, 3, 5)
    b = _f32(6)
    got = np.asarray(V.bilinear_tensor_product(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(w), jnp.asarray(b)))
    ref = np.stack([np.sum(x @ w[k] * y, axis=1) for k in range(6)], 1) + b
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_fsp():
    x, y = _f32(2, 3, 4, 5), _f32(2, 6, 4, 5)
    got = np.asarray(V.fsp(jnp.asarray(x), jnp.asarray(y)))
    ref = np.einsum("nchw,ndhw->ncd", x, y) / 20.0
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_add_position_encoding():
    x = _f32(2, 4, 6)
    got = np.asarray(V.add_position_encoding(jnp.asarray(x), 0.5, 2.0))
    assert got.shape == x.shape
    # beta*PE at position 0: sin(0)=0 for first half, cos(0)=1 for second
    np.testing.assert_allclose(got[:, 0, :3], 0.5 * x[:, 0, :3], atol=1e-6)
    np.testing.assert_allclose(got[:, 0, 3:], 0.5 * x[:, 0, 3:] + 2.0,
                               atol=1e-6)


def test_im2sequence():
    x = _f32(1, 2, 4, 4)
    out = np.asarray(V.im2sequence(jnp.asarray(x), (2, 2), (2, 2)))
    assert out.shape == (4, 8)
    # first window = x[:, :, 0:2, 0:2]
    np.testing.assert_allclose(out[0], x[0, :, 0:2, 0:2].reshape(-1))


def test_partial_concat_sum():
    a, b = _f32(3, 6), _f32(3, 6)
    got = np.asarray(V.partial_concat([jnp.asarray(a), jnp.asarray(b)],
                                      1, 2))
    np.testing.assert_allclose(got, np.concatenate(
        [a[:, 1:3], b[:, 1:3]], 1))
    got2 = np.asarray(V.partial_sum([jnp.asarray(a), jnp.asarray(b)], 1, 2))
    np.testing.assert_allclose(got2, a[:, 1:3] + b[:, 1:3], rtol=1e-6)


def test_batch_fc():
    x = _f32(3, 4, 5)
    w = _f32(3, 5, 2)
    b = _f32(3, 2)
    got = np.asarray(V.batch_fc(jnp.asarray(x), jnp.asarray(w),
                                jnp.asarray(b)))
    ref = np.einsum("snd,sde->sne", x, w) + b[:, None]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_shuffle_batch_permutes():
    x = jnp.arange(10.0)[:, None]
    y, idx = V.shuffle_batch(x, key=jax.random.PRNGKey(0))
    assert sorted(np.asarray(y)[:, 0].tolist()) == list(range(10))
    np.testing.assert_allclose(np.asarray(x)[np.asarray(idx), 0],
                               np.asarray(y)[:, 0])


def test_max_unpool2d_roundtrip():
    x = _f32(2, 3, 4, 4)
    pooled, idx = NF.max_pool2d(jnp.asarray(x), 2, 2, return_mask=True)
    restored = V.max_unpool2d(pooled, idx, 2, 2)
    assert restored.shape == x.shape
    # every pooled max lands back at its argmax position
    flat = np.asarray(restored).reshape(2, 3, -1)
    pooled_np = np.asarray(pooled).reshape(2, 3, -1)
    idx_np = np.asarray(idx).reshape(2, 3, -1)
    for n in range(2):
        for c in range(3):
            np.testing.assert_allclose(flat[n, c][idx_np[n, c]],
                                       pooled_np[n, c])
    # non-argmax positions are zero
    assert np.count_nonzero(np.asarray(restored)) <= 2 * 3 * 4


def test_spp():
    x = _f32(2, 3, 8, 8)
    out = V.spp(jnp.asarray(x), 2, "max")
    assert out.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(np.asarray(out)[:, :3],
                               x.max((2, 3)), rtol=1e-6)
    out_avg = V.spp(jnp.asarray(x), 1, "avg")
    np.testing.assert_allclose(np.asarray(out_avg), x.mean((2, 3)),
                               rtol=1e-5)


def test_psroi_pool():
    # constant feature map -> every bin equals the constant of its channel
    oc, ph, pw = 2, 2, 2
    # reference layout (psroi_pool_op.cc): channel (c*ph + i)*pw + j feeds
    # output class c at bin (i, j)
    x = np.zeros((1, oc * ph * pw, 8, 8), np.float32)
    for k in range(oc * ph * pw):
        x[0, k] = k
    rois = np.array([[0.0, 0.0, 8.0, 8.0]], np.float32)
    out = np.asarray(V.psroi_pool(jnp.asarray(x), jnp.asarray(rois),
                                  oc, 1.0, ph, pw))
    assert out.shape == (1, oc, ph, pw)
    for i in range(ph):
        for j in range(pw):
            for c in range(oc):
                assert out[0, c, i, j] == (c * ph + i) * pw + j


def test_prroi_pool_constant():
    x = np.full((1, 3, 6, 6), 2.5, np.float32)
    rois = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
    out = np.asarray(V.prroi_pool(jnp.asarray(x), jnp.asarray(rois),
                                  1.0, 2, 2))
    np.testing.assert_allclose(out, 2.5, rtol=1e-5)


def test_deformable_conv_zero_offset_matches_conv():
    x = _f32(1, 4, 6, 6)
    w = _f32(5, 4, 3, 3)
    offset = np.zeros((1, 2 * 9, 4, 4), np.float32)
    got = V.deformable_conv(jnp.asarray(x), jnp.asarray(offset),
                            jnp.asarray(w), stride=1, padding=0)
    ref = NF.conv2d(jnp.asarray(x), jnp.asarray(w), stride=1, padding=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_v2_mask_scales():
    x = _f32(1, 2, 5, 5)
    w = _f32(3, 2, 3, 3)
    offset = np.zeros((1, 18, 3, 3), np.float32)
    mask_half = np.full((1, 9, 3, 3), 0.5, np.float32)
    full = V.deformable_conv(jnp.asarray(x), jnp.asarray(offset),
                             jnp.asarray(w))
    half = V.deformable_conv(jnp.asarray(x), jnp.asarray(offset),
                             jnp.asarray(w), mask=jnp.asarray(mask_half))
    np.testing.assert_allclose(np.asarray(half), 0.5 * np.asarray(full),
                               rtol=1e-4, atol=1e-5)


def test_conv3d_transpose_shape_and_grad():
    x = jnp.asarray(_f32(1, 2, 3, 4, 4))
    w = jnp.asarray(_f32(2, 3, 2, 2, 2))  # [Cin, Cout, kd, kh, kw]
    out = V.conv3d_transpose(x, w, stride=2)
    assert out.shape == (1, 3, 6, 8, 8)
    g = jax.grad(lambda a: V.conv3d_transpose(a, w, stride=2).sum())(x)
    assert np.isfinite(np.asarray(g)).all()
    # sum preservation under stride-1 full transpose conv of ones kernel
    w1 = jnp.ones((2, 1, 2, 2, 2))
    out1 = V.conv3d_transpose(x, w1, stride=1)
    np.testing.assert_allclose(float(out1.sum()),
                               float(x.sum()) * 8, rtol=1e-4)


def test_correlation_self_positive():
    x = _f32(1, 4, 6, 6)
    out = V.correlation(jnp.asarray(x), jnp.asarray(x), pad_size=2,
                        kernel_size=1, max_displacement=2)
    assert out.shape == (1, 25, 6, 6)
    # center displacement (0,0) channel = mean over C of x*x >= 0
    center = np.asarray(out)[0, 12]
    np.testing.assert_allclose(center, (x[0] ** 2).mean(0), rtol=1e-5)


def test_yolov3_loss_runs_and_grads():
    n, cn = 2, 4
    h = w = 4
    anchors = [10, 13, 16, 30, 33, 23]
    mask = [0, 1, 2]
    a = len(mask)
    x = jnp.asarray(_f32(n, a * (5 + cn), h, w))
    gt_box = jnp.asarray(np.array(
        [[[0.5, 0.5, 0.3, 0.4], [0.2, 0.3, 0.1, 0.2]],
         [[0.7, 0.2, 0.2, 0.1], [0.0, 0.0, 0.0, 0.0]]], np.float32))
    gt_label = jnp.asarray(np.array([[1, 2], [3, 0]], np.int32))
    loss = V.yolov3_loss(x, gt_box, gt_label, anchors, mask, cn,
                         ignore_thresh=0.7, downsample_ratio=32)
    assert loss.shape == (n,)
    assert np.isfinite(np.asarray(loss)).all() and (np.asarray(loss) > 0).all()
    g = jax.grad(lambda xx: V.yolov3_loss(
        xx, gt_box, gt_label, anchors, mask, cn, 0.7, 32).sum())(x)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0
    # padded gt (zero w/h) contributes nothing: zeroing it changes nothing
    loss2 = V.yolov3_loss(x, gt_box.at[1, 1].set(0.0), gt_label, anchors,
                          mask, cn, 0.7, 32)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(loss2),
                               rtol=1e-6)


def test_yolov3_loss_under_jit():
    n, cn, h = 1, 3, 4
    anchors = [10, 13, 16, 30]
    mask = [0, 1]
    x = jnp.asarray(_f32(n, len(mask) * (5 + cn), h, h))
    gt_box = jnp.asarray(np.array([[[0.4, 0.6, 0.2, 0.2]]], np.float32))
    gt_label = jnp.asarray(np.array([[1]], np.int32))
    f = jax.jit(lambda a, b, c: V.yolov3_loss(
        a, b, c, anchors, mask, cn, 0.5, 32))
    l1 = f(x, gt_box, gt_label)
    l2 = V.yolov3_loss(x, gt_box, gt_label, anchors, mask, cn, 0.5, 32)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-5)


def test_registry_has_vision_extras():
    from paddle_tpu.ops.registry import has_op
    for name in ["affine_channel", "space_to_depth", "shuffle_channel",
                 "cvm", "shuffle_batch", "partial_concat", "partial_sum",
                 "batch_fc", "row_conv", "conv_shift", "im2sequence",
                 "add_position_encoding", "fsp", "bilinear_tensor_product",
                 "correlation", "max_unpool2d", "unpool", "spp",
                 "psroi_pool", "prroi_pool", "deformable_conv",
                 "conv3d_transpose", "yolov3_loss"]:
        assert has_op(name), name
