"""Eager <-> traced training/inference parity at the model level.

Reference parity: the dygraph_to_static end-to-end suite
(unittests/dygraph_to_static/test_resnet.py, test_bert.py, ...) trains a
few steps in dygraph and in the translated static program and asserts the
loss trajectories agree. Same contract here across this framework's three
execution modes: the eager tape loop, the fused jitted TrainStep, and the
traced Program / to_static forward.
"""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as optim
from paddle_tpu import nn
from paddle_tpu.jit import TrainStep

pytestmark = pytest.mark.slow  # covered breadth; fast lane keeps sibling smokes


def _make_cnn():
    pt.seed(7)
    return nn.Sequential(
        nn.Conv2D(1, 8, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Flatten(), nn.Linear(8 * 4 * 4, 10))


def _cnn_batches(n=6):
    rng = np.random.default_rng(3)
    return [(rng.standard_normal((8, 1, 8, 8)).astype("float32"),
             (rng.integers(0, 10, 8)).astype("int64")) for _ in range(n)]


def _eager_losses(model, batches, lr=0.1):
    opt = optim.SGD(learning_rate=lr, parameters=model.parameters())
    losses = []
    for x, y in batches:
        loss = nn.functional.cross_entropy(model(pt.to_tensor(x)),
                                           pt.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


def test_cnn_eager_vs_trainstep_loss_trajectory():
    """The fused one-launch TrainStep must reproduce the eager tape's
    loss trajectory step for step (same init, same data, SGD)."""
    batches = _cnn_batches()
    eager_model = _make_cnn()
    eager_losses = _eager_losses(eager_model, batches)

    step_model = _make_cnn()  # same seed -> identical init
    step = TrainStep(step_model, optim.SGD(learning_rate=0.1),
                     lambda m, b: nn.functional.cross_entropy(
                         m(b[0]), b[1]))
    step_losses = [float(step(b)) for b in batches]
    np.testing.assert_allclose(step_losses, eager_losses, rtol=2e-4,
                               atol=2e-5)
    # and the resulting weights agree
    for (n1, p1), (n2, p2) in zip(
            sorted(dict(eager_model.named_parameters()).items()),
            sorted(step.params.items())):
        np.testing.assert_allclose(
            np.asarray(p1.value), np.asarray(p2), rtol=2e-3, atol=2e-4,
            err_msg=f"{n1} vs {n2}")


def test_cnn_multi_step_scan_matches_python_loop():
    """multi_step (lax.scan over stacked batches — the production hot
    loop) must match per-call stepping exactly."""
    batches = _cnn_batches(4)
    m1 = _make_cnn()
    s1 = TrainStep(m1, optim.Adam(learning_rate=1e-3),
                   lambda m, b: nn.functional.cross_entropy(m(b[0]),
                                                            b[1]))
    per_call = [float(s1(b)) for b in batches]

    m2 = _make_cnn()
    s2 = TrainStep(m2, optim.Adam(learning_rate=1e-3),
                   lambda m, b: nn.functional.cross_entropy(m(b[0]),
                                                            b[1]))
    stacked = (np.stack([b[0] for b in batches]),
               np.stack([b[1] for b in batches]))
    scanned = np.asarray(s2.multi_step(stacked))
    np.testing.assert_allclose(scanned, per_call, rtol=2e-4, atol=2e-5)


def test_gpt_eager_vs_to_static_forward_parity():
    """to_static-captured forward == eager forward on the same weights
    (the reference checks translated-program parity for BERT/GPT-class
    models)."""
    from paddle_tpu import jit
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    model.eval()
    ids = pt.to_tensor((np.arange(2 * 16).reshape(2, 16) % 50).astype(
        np.int32))
    eager_logits = np.asarray(model(ids).value)

    static_model = jit.to_static(model)
    static_logits = np.asarray(static_model(ids).value)
    np.testing.assert_allclose(static_logits, eager_logits, rtol=2e-4,
                               atol=2e-5)


def test_cnn_program_capture_matches_eager_inference():
    """build_program (the ProgramDesc analog) and the serving Predictor
    reproduce eager inference numerics."""
    import paddle_tpu.inference as inference
    import paddle_tpu.static as st

    model = _make_cnn()
    model.eval()
    x = np.random.default_rng(9).standard_normal(
        (4, 1, 8, 8)).astype("float32")
    eager_out = np.asarray(model(pt.to_tensor(x)).value)

    prog = st.build_program(model, [st.InputSpec([4, 1, 8, 8],
                                                 name="x")])
    prog_out = np.asarray(prog.run(x))
    np.testing.assert_allclose(prog_out, eager_out, rtol=2e-4, atol=2e-5)

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        prefix = f"{d}/cnn"
        prog.save(prefix)
        pred = inference.create_predictor(inference.Config(prefix))
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        pred.run()
        served = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(served, eager_out, rtol=2e-4, atol=2e-5)


def test_rnn_model_eager_vs_trainstep():
    """Recurrent models (scan-based kernels) keep mode parity too."""
    def build():
        pt.seed(11)

        class TinyLM(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(32, 16)
                self.gru = nn.GRU(16, 16)
                self.head = nn.Linear(16, 32)

            def forward(self, ids):
                out, _ = self.gru(self.emb(ids))
                return self.head(out)

        return TinyLM()

    rng = np.random.default_rng(5)
    batches = [(rng.integers(0, 32, (4, 10)).astype(np.int64),
                rng.integers(0, 32, (4, 10)).astype(np.int64))
               for _ in range(4)]

    def loss_fn(m, b):
        logits = m(b[0])
        return nn.functional.cross_entropy(
            logits.reshape((-1, 32)), b[1].reshape((-1,)))

    m1 = build()
    opt = optim.Adam(learning_rate=1e-3, parameters=m1.parameters())
    eager = []
    for b in batches:
        loss = loss_fn(m1, tuple(pt.to_tensor(v) for v in b))
        loss.backward()
        opt.step()
        opt.clear_grad()
        eager.append(float(loss.numpy()))

    m2 = build()
    step = TrainStep(m2, optim.Adam(learning_rate=1e-3), loss_fn)
    fused = [float(step(b)) for b in batches]
    np.testing.assert_allclose(fused, eager, rtol=2e-4, atol=2e-5)


def test_bert_masked_positions_head_matches_full():
    """The gathered MLM head (reference max_predictions_per_seq data
    format) computes the same loss as the full-sequence head when the
    positions cover exactly the labeled slots."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models.bert import BertForPretraining, bert_tiny

    pt.seed(0)
    m = BertForPretraining(bert_tiny())
    rng = np.random.default_rng(0)
    B, S, K = 2, 32, 5
    ids = rng.integers(0, 128, (B, S)).astype(np.int32)
    pos = np.stack([rng.choice(S, K, replace=False) for _ in range(B)]) \
        .astype(np.int32)
    labels_full = np.full((B, S), -100, np.int64)
    for b in range(B):
        labels_full[b, pos[b]] = ids[b, pos[b]]

    l_full = float(m(pt.to_tensor(ids), labels=pt.to_tensor(labels_full)))
    l_gath = float(m(pt.to_tensor(ids),
                     masked_positions=pt.to_tensor(pos),
                     labels=pt.to_tensor(labels_full)))
    np.testing.assert_allclose(l_gath, l_full, rtol=1e-5)
    # gathered labels [B, K] work too
    l_gath2 = float(m(pt.to_tensor(ids),
                      masked_positions=pt.to_tensor(pos),
                      labels=pt.to_tensor(
                          np.take_along_axis(labels_full, pos, 1))))
    np.testing.assert_allclose(l_gath2, l_full, rtol=1e-5)
