"""Multi-process distributed worker model script.

TestDistBase analog (reference:
python/paddle/fluid/tests/unittests/test_dist_base.py:743 spawns worker
scripts like dist_mnist.py as localhost subprocesses with the fleetrun
env contract, then asserts loss parity between 1-proc and N-proc runs).

This script is launched by tests/test_dist_procs.py (directly for the
1-proc baseline, via paddle_tpu.distributed.launch for N procs). Each
process: force CPU with PT_LOCAL_DEVICES virtual devices, bootstrap
jax.distributed through init_parallel_env (gloo cross-process
collectives), build the fleet mesh over ALL global devices, and train
GPT-tiny on deterministic synthetic data. Per-step losses are written to
``$PT_DIST_OUT.<rank>`` as JSON.

Env contract (set by the launcher / test):
  PT_PROCESS_ID / PT_NUM_PROCESSES / PT_COORDINATOR_ADDRESS  bootstrap
  PT_LOCAL_DEVICES   virtual CPU devices per process (default 2)
  PT_DIST_STEPS      training steps (default 4)
  PT_DIST_BATCH      global batch size (default 8)
  PT_DIST_HYBRID     "dp" (default) or "dp_mp" (mp_degree=2 hybrid)
  PT_DIST_OUT        output path prefix for the loss JSON
  PT_DIST_CKPT       checkpoint path; save each step, resume if present
  PT_DIST_FAIL_RANK / PT_DIST_FAIL_STEP / PT_DIST_FAIL_ONCE_FILE
                     simulate a transient crash: that rank exits with
                     ELASTIC_EXIT_CODE at the start of that step, once —
                     the marker file records that the crash already
                     happened so the elastic relaunch completes
"""

import json
import os
import pickle


def save_ckpt(path, step_obj, next_step):
    """Atomic full-state checkpoint (params + optimizer state).

    dp-only meshes keep params/slots replicated, so np.asarray of the
    global arrays is process-local-safe."""
    import jax
    import numpy as np
    state = {
        "next_step": next_step,
        "params": {n: np.asarray(v) for n, v in step_obj.params.items()},
        "buffers": {n: np.asarray(v)
                    for n, v in step_obj.buffers.items()},
        "opt": jax.tree_util.tree_map(lambda v: np.asarray(v),
                                      step_obj.opt_state),
    }
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        pickle.dump(state, f)
    os.replace(tmp, path)


def load_ckpt(path, step_obj):
    import jax
    with open(path, "rb") as f:
        state = pickle.load(f)
    step_obj.params = {
        n: jax.device_put(v, step_obj.param_shardings[n])
        for n, v in state["params"].items()}
    step_obj.buffers = {
        n: jax.device_put(v, step_obj.buffer_shardings[n])
        for n, v in state["buffers"].items()}
    shardings = {"slots": step_obj.opt_shardings["slots"],
                 "step": step_obj.opt_shardings["step"]}
    step_obj.opt_state = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, s), state["opt"], shardings)
    return state["next_step"]


def main():
    local_dev = os.environ.get("PT_LOCAL_DEVICES", "2")
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_dev}")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.distributed.env import init_parallel_env
    init_parallel_env()

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import optimizer as optim
    from paddle_tpu.distributed import DistributedStrategy, fleet
    from paddle_tpu.distributed.elastic import ELASTIC_EXIT_CODE
    from paddle_tpu.models import GPTForCausalLM, gpt_tiny

    rank = jax.process_index()
    n_dev = jax.device_count()

    strategy = DistributedStrategy()
    if os.environ.get("PT_DIST_HYBRID", "dp") == "dp_mp":
        strategy.hybrid_configs = {"dp_degree": n_dev // 2, "mp_degree": 2}
    else:
        strategy.hybrid_configs = {"dp_degree": n_dev}
    fleet.init(is_collective=True, strategy=strategy)

    pt.seed(42)
    model = GPTForCausalLM(gpt_tiny())
    opt = optim.SGD(learning_rate=0.1)
    step = fleet.distributed_jit(model, opt,
                                 lambda m, b: m(b[0], labels=b[1]))

    steps = int(os.environ.get("PT_DIST_STEPS", "4"))
    batch = int(os.environ.get("PT_DIST_BATCH", "8"))
    fail_rank = int(os.environ.get("PT_DIST_FAIL_RANK", "-1"))
    fail_step = int(os.environ.get("PT_DIST_FAIL_STEP", "-1"))
    ckpt = os.environ.get("PT_DIST_CKPT")

    start = 0
    if ckpt and os.path.exists(ckpt):
        start = load_ckpt(ckpt, step)

    fail_once = os.environ.get("PT_DIST_FAIL_ONCE_FILE")
    losses = []
    for i in range(start, steps):
        if (i == fail_step and rank == fail_rank and fail_once
                and not os.path.exists(fail_once)):
            with open(fail_once, "w") as f:
                f.write("crashed")
            os._exit(ELASTIC_EXIT_CODE)
        # global batch is a pure function of the step index: every
        # process generates the same array; device_put shards it
        rng = np.random.default_rng(1000 + i)
        ids = rng.integers(0, 1024, size=(batch, 32)).astype(np.int32)
        losses.append(float(step((ids, ids))))
        if ckpt and rank == 0:
            save_ckpt(ckpt, step, i + 1)

    out = os.environ.get("PT_DIST_OUT")
    if out:
        with open(f"{out}.{rank}", "w") as f:
            json.dump({"rank": rank, "world": jax.process_count(),
                       "n_dev": n_dev, "start": start,
                       "losses": losses}, f)
    print(json.dumps(losses), flush=True)


if __name__ == "__main__":
    main()
