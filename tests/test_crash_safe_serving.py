"""Crash-safe serving (r9): engine resurrection with in-flight replay,
deadline propagation, stall watchdog, replica supervision, and the
seeded chaos harness (tools/chaos_serving.py).

The contracts pinned here (ISSUE r9 acceptance):

- a persistent engine-step failure is survived by RESURRECTION —
  teardown (pages audited), rebuild, and replay of every in-flight
  request, with greedy outputs BIT-IDENTICAL to the uninterrupted run;
- ``deadline_ms`` produces a typed DeadlineExceeded (never a hang, no
  leaked pages) at EVERY lifecycle stage: queued, mid-prefill,
  mid-decode, and mid-speculative-run;
- the chaos harness invariants hold with engine.step + alloc.page +
  net.recv armed and one replica SIGKILLed: 100% typed termination,
  clean per-replica leak audits after drain, bit-identical replayed
  outputs.
"""

import importlib.util
import os
import pathlib
import re
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed import fault_inject as fi
from paddle_tpu.distributed.resilience import (_BUILTIN_SITE_POLICIES,
                                               NO_RETRY_SITES)
from paddle_tpu.inference import SpeculativeConfig, create_decode_engine
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import (ServingMetrics, ServingServer,
                                client_request)

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_injector():
    fi.reset()
    yield
    fi.reset()


@pytest.fixture(scope="module", autouse=True)
def _compile_cache(module_compile_cache):
    """Engine-heavy file: reuse XLA compiles across tests (see
    conftest.module_compile_cache) — most of this file's tier-1 wall
    cost is repeated compiles of the same gpt_tiny shapes."""
    yield


@pytest.fixture(scope="module")
def model():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


ENGINE_KW = dict(num_slots=2, page_size=8, max_seq_len=96, num_pages=12)


def _engine(m, **kw):
    merged = dict(ENGINE_KW)
    merged.update(kw)
    return create_decode_engine(m, **merged)


def _server(m, **kw):
    merged = dict(ENGINE_KW)
    merged.update(kw)
    merged.setdefault("metrics", ServingMetrics(registry=StatRegistry()))
    return ServingServer(m, **merged)


def _gen(port, payload, timeout_s=180.0, on_token=None):
    return client_request("127.0.0.1", port, payload,
                          timeout_s=timeout_s, on_token=on_token)


# ---------------------------------------------------------------------------
# Engine resurrection: bit-identical replay (tentpole pin)
# ---------------------------------------------------------------------------

class TestResurrection:
    def _expected(self, model, prompts, mnt):
        eng = _engine(model)
        rids = [eng.submit(np.asarray(p, np.int32), mnt)
                for p in prompts]
        results = eng.run()
        eng.close()
        return [[int(t) for t in results[r][len(p):]]
                for r, p in zip(rids, prompts)]

    def test_replay_bit_identical_streams_and_finals(self, model):
        """Two in-flight requests survive an engine death mid-decode:
        the rebuilt engine replays prompt + emitted tokens as one
        chained prefill, the clients' STREAMS carry no duplicates and
        no gaps, and the final sequences equal the fault-free run."""
        prompts = [list(range(1, 7)), list(range(3, 12))]
        expected = self._expected(model, prompts, 8)
        # two consecutive step faults at calls 3,4 breach
        # max_engine_errors=2 while both requests are mid-decode
        fi.get_injector().arm("engine.step", at_calls=[3, 4])
        met = ServingMetrics(registry=StatRegistry())
        srv = _server(model, metrics=met, max_engine_errors=2)
        port = srv.start()
        results = [None, None]
        toks = [[], []]

        def client(i):
            results[i] = _gen(port, {
                "op": "generate", "prompt": prompts[i],
                "max_new_tokens": 8, "stream": True},
                on_token=toks[i].append)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        for i in range(2):
            assert results[i] is not None, "client hung"
            assert "error" not in results[i], results[i]
            assert results[i]["generated"] == expected[i]
            assert toks[i] == expected[i]  # pause, no dup, no gap
            assert results[i]["stats"].get("replayed") is True
            assert results[i]["tokens"] == \
                prompts[i] + expected[i]
        counters = met.snapshot()["counters"]
        assert counters["engine_restarts_total"] == 1
        assert counters["replayed_requests_total"] == 2
        # telemetry is stitched too: every token a client received is
        # counted exactly once, pre-crash tokens included — not just
        # the post-resurrection slice
        assert counters["tokens_generated_total"] == \
            sum(len(e) for e in expected)
        # the server still serves new work after resurrection
        rep = _gen(port, {"op": "generate", "prompt": [5, 6, 7],
                          "max_new_tokens": 3})
        assert "error" not in rep and len(rep["generated"]) == 3
        chk = _gen(port, {"op": "leak_check"})
        assert chk["ok"], chk
        srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_replay_survives_short_prompt_bucket_ladder(self, model):
        """A custom prompt_buckets ladder that stops short of
        max_seq_len must not turn a transparent replay into
        ReplayFailed: replay submits prompt + emitted tokens as ONE
        chained prefill, so the server extends the ladder to
        max_seq_len (prefill jits retrace per shape lazily — the extra
        bucket is free until used)."""
        prompts = [list(range(1, 16))]  # 15 tokens: fits bucket 16,
        expected = self._expected(model, prompts, 8)  # replay won't
        fi.get_injector().arm("engine.step", at_calls=[3, 4])
        srv = _server(model, max_engine_errors=2, prompt_buckets=(16,))
        assert srv.engine.prompt_buckets[-1] == ENGINE_KW["max_seq_len"]
        port = srv.start()
        rep = _gen(port, {"op": "generate", "prompt": prompts[0],
                          "max_new_tokens": 8})
        assert "error" not in rep, rep
        assert rep["generated"] == expected[0]
        assert rep["stats"].get("replayed") is True
        srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_restart_budget_exhausted_escalates_typed(self, model):
        """engine.step failing FOREVER: resurrection is tried
        max_engine_restarts times, then the server fails everything
        typed and stops admitting — never an untyped wedge."""
        fi.get_injector().arm("engine.step", probability=1.0)
        srv = _server(model, max_engine_errors=2,
                      max_engine_restarts=1)
        port = srv.start()
        rep = _gen(port, {"op": "generate", "prompt": [1, 2, 3],
                          "max_new_tokens": 4}, timeout_s=90)
        assert rep.get("error") in ("EngineFailed", "ServerEvicted"), rep
        h = _gen(port, {"op": "health"})
        assert h["status"] == "draining"
        assert h["engine_restarts"] == 1
        srv.stop()
        srv.engine.allocator.check_no_leak()

    def test_queued_requests_replay_too(self, model):
        """Requests still QUEUED at engine death (never prefilled) ride
        the same replay path with an empty pre-crash history."""
        prompts = [list(range(1, 20)), list(range(2, 21)),
                   list(range(3, 22))]  # 3 requests, 2 slots: one queues
        expected = self._expected(model, prompts, 6)
        fi.get_injector().arm("engine.step", at_calls=[3, 4])
        srv = _server(model, max_engine_errors=2)
        port = srv.start()
        results = [None] * 3

        def client(i):
            results[i] = _gen(port, {
                "op": "generate", "prompt": prompts[i],
                "max_new_tokens": 6})

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        for i in range(3):
            assert results[i] is not None and \
                "error" not in results[i], results[i]
            assert results[i]["generated"] == expected[i]
        srv.stop()
        srv.engine.allocator.check_no_leak()


# ---------------------------------------------------------------------------
# Deadline propagation: typed expiry at every lifecycle stage
# ---------------------------------------------------------------------------

class TestDeadlineLifecycle:
    def test_expired_in_queue_shed_before_prefill(self, model):
        done = []
        eng = _engine(model, on_complete=done.append)
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 4,
                         deadline_t=time.monotonic() - 0.01)
        eng.step()
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "deadline"
        assert req.stats.prefill_attempts == 0  # shed BEFORE prefill
        assert req.stats.tokens_out == 0
        eng.allocator.check_no_leak()

    def test_expired_mid_prefill_unwinds_typed(self, model):
        done = []
        eng = _engine(model, on_complete=done.append)
        orig_get = eng._get_prefill

        def slow_get(chained):
            jit = orig_get(chained)

            def wrapped(*a, **kw):
                time.sleep(0.15)
                return jit(*a, **kw)
            return wrapped

        eng._get_prefill = slow_get
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 4,
                         deadline_t=time.monotonic() + 0.05)
        eng.step()  # admission prefill outlives the deadline
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "deadline"
        assert req.stats.prefill_attempts == 1  # prefill DID run
        assert req.stats.tokens_out == 0        # but nothing delivered
        assert eng.num_active == 0
        eng.allocator.check_no_leak()

    def test_expired_mid_decode_evicts_and_returns_pages(self, model):
        done = []
        eng = _engine(model, on_complete=done.append)
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 12,
                         deadline_t=time.monotonic() + 3600)
        eng.step()
        eng.step()
        (req,) = [r for r in eng._slots if r is not None]
        req.deadline_t = time.monotonic() - 0.01  # force expiry
        eng.step()
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "deadline"
        assert 1 <= req.stats.tokens_out < 12  # partial, then evicted
        assert eng.num_active == 0
        eng.allocator.check_no_leak()

    def test_expired_mid_speculative_run_frees_reservation(self, model):
        done = []
        eng = _engine(model, on_complete=done.append,
                      speculative=SpeculativeConfig(k=2, draft="ngram"))
        rid = eng.submit(np.arange(1, 10, dtype=np.int32), 24,
                         deadline_t=time.monotonic() + 3600)
        eng.step()
        eng.step()
        assert eng.allocator.reserved_total > 0  # spec admission held
        (req,) = [r for r in eng._slots if r is not None]
        req.deadline_t = time.monotonic() - 0.01
        eng.step()
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "deadline"
        assert eng.allocator.reserved_total == 0  # reservation returned
        eng.allocator.check_no_leak()

    def test_hopeless_deadline_never_admitted(self, model):
        """The admission gate: with a step-time estimate available, a
        request whose token budget cannot fit its deadline is expired
        typed instead of wasting a prefill."""
        done = []
        eng = _engine(model, on_complete=done.append)
        eng.submit(np.arange(1, 4, dtype=np.int32), 4)
        while eng.num_active or eng.num_queued:
            eng.step()  # warm: establishes step_ema_s
        assert eng.step_ema_s is not None
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 64,
                         deadline_t=time.monotonic()
                         + eng.step_ema_s)  # 64 tokens in ~1 step: no
        eng.step()
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "deadline"
        assert req.stats.prefill_attempts == 0
        eng.allocator.check_no_leak()

    def test_hopeless_gate_is_best_case_not_expected(self, model):
        """The gate must use a provable LOWER bound on remaining work:
        an eos_token can end the generation after one token and a
        speculative step emits up to k+1 tokens, so neither request
        below is provably hopeless even though max_new_tokens * ema
        overshoots the budget."""
        eng = _engine(model)
        eng.step_ema_s = 0.01
        now = time.monotonic()
        # 64-token CAP but eos could finish it in one step: feasible
        eng.submit(np.arange(1, 6, dtype=np.int32), 64, eos_token=2,
                   deadline_t=now + 5 * eng.step_ema_s)
        assert not eng._deadline_hopeless(eng._queue[-1], now)
        # same budget without eos: provably needs 64 steps — hopeless
        eng.submit(np.arange(1, 6, dtype=np.int32), 64,
                   deadline_t=now + 5 * eng.step_ema_s)
        assert eng._deadline_hopeless(eng._queue[-1], now)
        # speculative k=3: 64 tokens can land in 16 verify steps
        spec = _engine(model, num_pages=24,
                       speculative=SpeculativeConfig(k=3, draft="ngram"))
        spec.step_ema_s = 0.01
        spec.submit(np.arange(1, 6, dtype=np.int32), 64,
                    deadline_t=now + 20 * spec.step_ema_s)
        assert not spec._deadline_hopeless(spec._queue[-1], now)

    def test_mid_prefill_expiry_charges_no_fairness(self, model):
        """A mid-prefill deadline unwind is NOT a committed admission:
        it must not reach scheduler.note_admitted (phantom bypass
        charges from deadline-tight traffic could starve the queue)."""
        class _SpyScheduler:
            def __init__(self):
                self.noted = []

            def select(self, queue, fits, now):
                for i, r in enumerate(queue):
                    if fits(r):
                        return i
                return None

            def shed(self, queue, now):
                return []

            def note_admitted(self, req, queue, now):
                self.noted.append(req.req_id)

        spy = _SpyScheduler()
        done = []
        eng = _engine(model, on_complete=done.append, scheduler=spy)
        orig_get = eng._get_prefill

        def slow_get(chained):
            jit = orig_get(chained)

            def wrapped(*a, **kw):
                time.sleep(0.15)
                return jit(*a, **kw)
            return wrapped

        eng._get_prefill = slow_get
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 4,
                         deadline_t=time.monotonic() + 0.05)
        eng.step()  # admission prefill outlives the deadline
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "deadline"
        assert spy.noted == []  # unwound admission: no fairness charge
        eng._get_prefill = orig_get
        rid2 = eng.submit(np.arange(1, 6, dtype=np.int32), 2)
        while not any(r.req_id == rid2 for r in done):
            eng.step()
        assert spy.noted == [rid2]  # committed admission IS charged
        eng.allocator.check_no_leak()

    def test_server_deadline_protocol(self, model):
        srv = _server(model)
        port = srv.start()
        # generous budget: completes normally
        rep = _gen(port, {"op": "generate", "prompt": [1, 2, 3],
                          "max_new_tokens": 4, "deadline_ms": 120000})
        assert "error" not in rep and len(rep["generated"]) == 4
        # doomed budget: typed DeadlineExceeded, never a hang
        rep = _gen(port, {"op": "generate", "prompt": [1, 2, 3],
                          "max_new_tokens": 4, "deadline_ms": 1})
        assert rep.get("error") == "DeadlineExceeded", rep
        # malformed budgets are BadRequest
        for bad in (-5, 0, "soon"):
            rep = _gen(port, {"op": "generate", "prompt": [1],
                              "max_new_tokens": 2, "deadline_ms": bad})
            assert rep.get("error") == "BadRequest", (bad, rep)
        st = _gen(port, {"op": "stats"})
        assert st["stats"]["counters"]["deadline_exceeded_total"] == 1
        srv.stop()
        srv.engine.allocator.check_no_leak()


# ---------------------------------------------------------------------------
# Stall watchdog (satellite)
# ---------------------------------------------------------------------------

class TestStallWatchdog:
    def test_stalled_slot_evicted_typed(self, model):
        done = []
        eng = _engine(model, stall_timeout_s=0.05,
                      on_complete=done.append)
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 12)
        eng.step()  # admit + first tokens
        time.sleep(0.1)  # no step() => no emission: a stall
        out = eng.evict_stalled()
        assert [r.req_id for r in out] == [rid]
        (req,) = [r for r in done if r.req_id == rid]
        assert req.state == "stalled"
        assert eng.num_active == 0
        eng.allocator.check_no_leak()

    def test_server_stalled_decoding_slot_typed(self, model):
        """A slot that was admitted and then starves (step faults
        forever after) gets RequestStalled with its pages back — via
        the sweep the serving loop runs when step() itself keeps
        raising."""
        met = ServingMetrics(registry=StatRegistry())
        srv = _server(model, stall_timeout_s=0.3, metrics=met,
                      max_engine_errors=10**6, max_engine_restarts=0)
        port = srv.start()
        got = {}
        first_tok = threading.Event()

        def client():
            got["rep"] = _gen(port, {"op": "generate",
                                     "prompt": [1, 2, 3],
                                     "max_new_tokens": 64,
                                     "stream": True},
                              timeout_s=120,
                              on_token=lambda t: first_tok.set())

        t = threading.Thread(target=client)
        t.start()
        # arm only once the request is ADMITTED and decoding (first
        # streamed token observed) — from then on every step fails and
        # the slot starves
        assert first_tok.wait(timeout=60), "request never started"
        fi.get_injector().arm("engine.step", probability=1.0)
        t.join(timeout=120)
        fi.reset()
        assert got.get("rep") is not None, "client hung"
        assert got["rep"].get("error") == "RequestStalled", got["rep"]
        assert met.snapshot()["counters"]["stalled_total"] == 1
        chk = _gen(port, {"op": "leak_check"})
        assert chk["ok"], chk
        srv.stop()


# ---------------------------------------------------------------------------
# Speculative drain/close leak audit (satellite)
# ---------------------------------------------------------------------------

class TestSpecDrainClose:
    def test_close_mid_spec_returns_reservations(self, model):
        eng = _engine(model,
                      speculative=SpeculativeConfig(k=2, draft="ngram"))
        eng.submit(np.arange(1, 10, dtype=np.int32), 24)
        eng.submit(np.arange(2, 8, dtype=np.int32), 24)
        eng.step()
        assert eng.allocator.reserved_total > 0
        eng.close()  # reserved-but-unallocated capacity must die here
        eng.allocator.check_no_leak()
        assert eng.allocator.free_count == eng.num_pages

    def test_server_stop_mid_spec_no_leak(self, model):
        srv = _server(model,
                      speculative=SpeculativeConfig(k=2, draft="ngram"))
        port = srv.start()
        got = {}

        def client():
            got["rep"] = _gen(port, {"op": "generate",
                                     "prompt": list(range(1, 10)),
                                     "max_new_tokens": 24})

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.2)  # mid-flight, reservations live
        srv.stop()
        t.join(timeout=60)
        assert got.get("rep") is not None, "client hung through stop()"
        srv.engine.allocator.check_no_leak()
        assert srv.engine.allocator.reserved_total == 0

    def test_check_no_leak_counts_dangling_reservation(self):
        from paddle_tpu.inference import PageAllocator
        alloc = PageAllocator(4)
        assert alloc.reserve("r", 2)
        with pytest.raises(RuntimeError, match="reserved"):
            alloc.check_no_leak()
        alloc.free("r")
        alloc.check_no_leak()


# ---------------------------------------------------------------------------
# Fault-site registry audit (satellite)
# ---------------------------------------------------------------------------

class TestFaultSiteAudit:
    def _source_files(self):
        # the PRODUCTION tree: tests may arm ad-hoc sites for unit
        # coverage of the injector itself
        roots = [REPO / "paddle_tpu", REPO / "tools"]
        for root in roots:
            yield from root.rglob("*.py")
        yield REPO / "bench_all.py"

    def test_every_used_site_is_registered_with_disposition(self):
        """Every site string passed to fault_point() anywhere in the
        tree must (a) be declared in fault_inject.FAULT_SITES with a
        docstring and (b) carry a retry disposition — a
        get_retry_policy entry or an explicit NO_RETRY_SITES marker."""
        pat = re.compile(r"fault_point\(\s*[\"']([a-z_.]+)[\"']")
        used = set()
        for f in self._source_files():
            used |= set(pat.findall(f.read_text(encoding="utf-8")))
        assert used, "audit regex found no fault_point call sites"
        unregistered = used - set(fi.FAULT_SITES)
        assert not unregistered, \
            f"fault sites used but not in FAULT_SITES: {unregistered}"
        for site, doc in fi.FAULT_SITES.items():
            assert isinstance(doc, str) and doc.strip(), \
                f"site {site!r} has no docstring"
        undisposed = (set(fi.FAULT_SITES)
                      - set(_BUILTIN_SITE_POLICIES)
                      - set(NO_RETRY_SITES))
        assert not undisposed, \
            f"sites with neither a retry policy nor an explicit " \
            f"no-retry marker: {undisposed}"
        ambiguous = set(_BUILTIN_SITE_POLICIES) & set(NO_RETRY_SITES)
        assert not ambiguous, \
            f"sites claiming BOTH retry and no-retry: {ambiguous}"

    def test_no_dead_registry_entries(self):
        """Every registered site appears as a string literal somewhere
        in the tree (catches registry entries outliving their call
        sites — including dynamic ones like ps.push/ps.pull/ps.call,
        which reach fault_point(site) through a variable)."""
        blob = "\n".join(f.read_text(encoding="utf-8")
                         for f in self._source_files())
        for site in fi.FAULT_SITES:
            assert f'"{site}"' in blob or f"'{site}'" in blob, \
                f"registered site {site!r} never appears in the tree"

    def test_no_retry_markers_have_reasons(self):
        for site, reason in NO_RETRY_SITES.items():
            assert isinstance(reason, str) and len(reason) > 10, \
                f"no-retry marker for {site!r} must explain who owns " \
                f"recovery"

    def test_injector_log_never_retains_tracebacks(self):
        """The injector's fired-fault log must hold traceback-FREE
        records: logging the raised exception itself pins every frame
        on the faulting stack (and whatever those frames reference —
        in the r9 chaos run, the torn connection's socket fd, turning
        a clean net.recv teardown into a 60s client hang because the
        FIN never left the process)."""
        fi.get_injector().arm("audit.retention", probability=1.0)
        sock_alive = {}

        def faulting_frame():
            # a frame-local standing in for the leaked socket: if the
            # raised exception's traceback is retained, this frame —
            # and the local — survive the except block
            import weakref

            class Resource:
                pass

            res = Resource()
            sock_alive["ref"] = weakref.ref(res)
            fi.fault_point("audit.retention")

        with pytest.raises(fi.InjectedFault):
            faulting_frame()
        log = fi.get_injector().log
        assert log, "fault fired but nothing logged"
        assert log[-1].__traceback__ is None, \
            "injector.log retained a RAISED exception (traceback pins " \
            "the faulting frames)"
        import gc
        gc.collect()
        assert sock_alive["ref"]() is None, \
            "faulting frame's locals survived the handled fault"


# ---------------------------------------------------------------------------
# Occupancy gauges + resurrection counters (satellite)
# ---------------------------------------------------------------------------

class TestMetricsGauges:
    def test_gauges_ride_snapshot_and_prometheus(self, model):
        srv = _server(model)
        port = srv.start()
        # a FRESH server must already export the declared counters at
        # 0 (absent-until-first-event counters break scrape-side
        # rate()/alerting) — probe before any request or stats call
        fresh = _gen(port, {"op": "metrics"})["text"]
        assert "serving_engine_restarts_total 0" in fresh
        assert "serving_replayed_requests_total 0" in fresh
        rep = _gen(port, {"op": "generate", "prompt": [1, 2, 3],
                          "max_new_tokens": 3})
        assert "error" not in rep
        st = _gen(port, {"op": "stats"})
        g = st["stats"]["gauges"]
        for key in ("inflight_slots", "queued_requests", "free_pages",
                    "reserved_pages", "prefix_cache_pages",
                    "num_pages"):
            assert key in g, (key, g)
        assert g["num_pages"] == 12
        assert g["free_pages"] + g["prefix_cache_pages"] == 12
        mx = _gen(port, {"op": "metrics"})["text"]
        assert "# TYPE serving_inflight_slots gauge" in mx
        assert "# TYPE serving_free_pages gauge" in mx
        assert "serving_engine_restarts_total 0" in mx
        assert "serving_replayed_requests_total 0" in mx
        srv.stop()

    def test_gauge_source_failure_never_kills_scrape(self):
        met = ServingMetrics(registry=StatRegistry())
        met.set_gauge_fn(lambda: (_ for _ in ()).throw(RuntimeError()))
        assert met.gauges() == {}
        assert "serving_ttft_ms" in met.prometheus_text()

    def test_health_reports_occupancy(self, model):
        srv = _server(model)
        port = srv.start()
        h = _gen(port, {"op": "health"})
        for key in ("reserved_pages", "cached_pages",
                    "engine_restarts", "step_ema_ms"):
            assert key in h, (key, h)
        srv.stop()


# ---------------------------------------------------------------------------
# Failover router over fake replicas (unit: no subprocesses)
# ---------------------------------------------------------------------------

class _FakeReplicaServer:
    """Protocol-speaking stand-in for a ServingServer process: streams
    ``n_tokens`` deterministic tokens then a final reply; optionally
    dies (closes the connection) after ``die_after`` token messages."""

    def __init__(self, n_tokens=6, die_after=None):
        import json as _json
        import socket as _socket
        self.n_tokens = n_tokens
        self.die_after = die_after
        self._json = _json
        self._sock = _socket.socket(_socket.AF_INET,
                                    _socket.SOCK_STREAM)
        self._sock.setsockopt(_socket.SOL_SOCKET,
                              _socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self.served = 0
        self.msgs = []
        self._stop = False
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while not self._stop:
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except OSError:
                continue
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        f = conn.makefile("rw", encoding="utf-8")
        try:
            line = f.readline()
            msg = self._json.loads(line)
            self.served += 1
            self.msgs.append(msg)
            for j in range(self.n_tokens):
                if self.die_after is not None and j >= self.die_after:
                    conn.close()  # died mid-stream
                    return
                f.write(self._json.dumps(
                    {"rid": 0, "token": 100 + j,
                     "done": j == self.n_tokens - 1}) + "\n")
                f.flush()
            f.write(self._json.dumps(
                {"rid": 0, "done": True,
                 "tokens": list(msg["prompt"])
                 + [100 + j for j in range(self.n_tokens)],
                 "generated": [100 + j for j in range(self.n_tokens)],
                 "stats": {}}) + "\n")
            f.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


class _FakeSupervisor:
    def __init__(self, servers):
        self.host = "127.0.0.1"
        self.replicas = []
        for i, s in enumerate(servers):
            rep = type("R", (), {})()
            rep.idx, rep.port, rep.ready = i, s.port, True
            rep.restarts = 0
            rep.alive = lambda: True
            self.replicas.append(rep)

    def live(self):
        return [r for r in self.replicas if r.ready]


class TestFailoverRouter:
    def test_keyed_request_fails_over_with_stream_dedupe(self):
        from paddle_tpu.serving.supervisor import FailoverRouter
        dying = _FakeReplicaServer(n_tokens=6, die_after=3)
        healthy = _FakeReplicaServer(n_tokens=6)
        sup = _FakeSupervisor([dying, healthy])
        router = FailoverRouter(sup, max_failover=3,
                                backend_timeout_s=10)
        port = router.start()
        toks = []
        # drive requests until one lands on the dying replica first
        for _ in range(4):
            toks.clear()
            rep = _gen(port, {"op": "generate", "prompt": [1, 2],
                              "max_new_tokens": 6, "stream": True,
                              "key": "k1"}, timeout_s=30,
                       on_token=toks.append)
            assert "error" not in rep, rep
            # dedupe contract: exactly one copy of each token, even
            # when the first 3 were relayed by the replica that died
            assert toks == [100 + j for j in range(6)]
            assert rep["generated"] == toks
            if dying.served and router.failovers_total:
                break
        assert router.failovers_total >= 1
        router.stop()
        dying.close()
        healthy.close()

    def test_unkeyed_request_gets_typed_replica_failed(self):
        from paddle_tpu.serving.supervisor import FailoverRouter
        dying = _FakeReplicaServer(n_tokens=6, die_after=2)
        sup = _FakeSupervisor([dying])
        router = FailoverRouter(sup, max_failover=3,
                                backend_timeout_s=10)
        port = router.start()
        rep = _gen(port, {"op": "generate", "prompt": [1],
                          "max_new_tokens": 6, "stream": True},
                   timeout_s=30)
        assert rep.get("error") == "ReplicaFailed", rep
        assert rep.get("retryable") is True
        router.stop()
        dying.close()

    def test_failover_carries_remaining_deadline_budget(self):
        """deadline_ms is a budget from ARRIVAL covering the whole
        request: every forward — the failover resubmission especially —
        must carry only the remaining budget, or each replica would
        restart the clock and the client could wait up to
        max_failover * deadline_ms."""
        from paddle_tpu.serving.supervisor import FailoverRouter
        dying = _FakeReplicaServer(n_tokens=6, die_after=3)
        healthy = _FakeReplicaServer(n_tokens=6)
        sup = _FakeSupervisor([dying, healthy])
        router = FailoverRouter(sup, max_failover=3,
                                backend_timeout_s=10)
        port = router.start()
        for _ in range(4):
            rep = _gen(port, {"op": "generate", "prompt": [1, 2],
                              "max_new_tokens": 6, "stream": True,
                              "key": "kb", "deadline_ms": 60_000},
                       timeout_s=30)
            assert "error" not in rep, rep
            if router.failovers_total:
                break
        assert router.failovers_total >= 1
        budgets = [m.get("deadline_ms") for s in (dying, healthy)
                   for m in s.msgs]
        assert budgets and all(
            isinstance(b, (int, float)) and 0 < b < 60_000
            for b in budgets), budgets
        router.stop()
        dying.close()
        healthy.close()

    def test_dead_client_is_not_a_dead_replica(self):
        """A send() failure toward the ROUTER'S client must abort the
        request quietly — not mark the healthy replica lost, not fail
        over (burning other replicas generating into a dead socket),
        and not corrupt the failover/replica-failure metrics."""
        from paddle_tpu.serving.supervisor import FailoverRouter
        healthy = _FakeReplicaServer(n_tokens=4)
        sup = _FakeSupervisor([healthy])
        router = FailoverRouter(sup, max_failover=3,
                                backend_timeout_s=10)
        sent = []

        def dying_send(obj):
            sent.append(obj)
            if len(sent) >= 2:  # client vanishes after the 1st token
                raise BrokenPipeError("client hung up")

        router._route_generate({"op": "generate", "prompt": [1, 2],
                                "max_new_tokens": 4, "stream": True,
                                "key": "k3"}, dying_send)
        assert router.failovers_total == 0
        assert router.replica_failures_total == 0
        assert healthy.served == 1  # no pointless resubmission
        router.stop()
        healthy.close()

    def test_router_net_recv_fault_triggers_failover(self):
        from paddle_tpu.serving.supervisor import FailoverRouter
        a = _FakeReplicaServer(n_tokens=4)
        b = _FakeReplicaServer(n_tokens=4)
        sup = _FakeSupervisor([a, b])
        router = FailoverRouter(sup, max_failover=3,
                                backend_timeout_s=10)
        port = router.start()
        fi.get_injector().arm("net.recv", at_calls=[2])
        rep = _gen(port, {"op": "generate", "prompt": [7],
                          "max_new_tokens": 4, "key": "k2",
                          "stream": True}, timeout_s=30)
        assert "error" not in rep, rep
        assert rep["generated"] == [100, 101, 102, 103]
        assert router.failovers_total >= 1
        router.stop()
        a.close()
        b.close()


class TestSupervisor:
    def test_never_ready_replica_is_reclaimed(self):
        """A replica process that stays alive but never answers a
        health probe (e.g. a hung compile during startup) must be
        killed and queued for respawn after ready_timeout_s — not run
        as permanent capacity loss."""
        import subprocess
        import sys
        from paddle_tpu.serving.supervisor import Supervisor
        sup = Supervisor(model="gpt_tiny", replicas=1,
                         probe_interval_s=0.05, probe_timeout_s=0.2,
                         ready_timeout_s=0.3, backoff_base_s=3600)
        rep = sup.replicas[0]
        rep.port = 1  # nothing listens: every probe fails
        rep.proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(600)"])
        rep.spawn_t = time.monotonic() - 10.0  # warmup long expired
        t = threading.Thread(target=sup._monitor_loop, daemon=True)
        t.start()
        try:
            for _ in range(100):
                if rep.next_spawn_t is not None:
                    break
                time.sleep(0.05)
            assert rep.next_spawn_t is not None, \
                "never-ready replica was not reclaimed"
            rep.proc.wait(timeout=5)  # killed, not leaked
        finally:
            sup._stop.set()
            t.join(timeout=2.0)
            if rep.proc.poll() is None:
                rep.proc.kill()


# ---------------------------------------------------------------------------
# Chaos harness (acceptance): seeded faults + SIGKILL, three invariants
# ---------------------------------------------------------------------------

def _load_chaos():
    import sys
    spec = importlib.util.spec_from_file_location(
        "chaos_serving", REPO / "tools" / "chaos_serving.py")
    mod = importlib.util.module_from_spec(spec)
    # sys.modules registration is REQUIRED: the module's dataclasses
    # resolve their (future-import) string annotations through
    # sys.modules[cls.__module__]
    sys.modules["chaos_serving"] = mod
    spec.loader.exec_module(mod)
    return mod


def _chaos_env_ok():
    # the harness spawns real server subprocesses; skip only where
    # subprocesses are impossible
    return os.access(REPO, os.R_OK)


class TestChaosHarness:
    def test_chaos_fast_lane_all_invariants(self):
        """Acceptance pin: engine.step + alloc.page + net.recv armed,
        one replica SIGKILLed — 100% typed termination, clean
        leak_check on every replica after drain, bit-identical greedy
        outputs on every success (replayed ones included)."""
        chaos = _load_chaos()
        report = chaos.run_chaos(replicas=2, requests=10, seed=0,
                                 kill_replica=True)
        assert report.ok, report.to_dict()
        assert report.hangs == 0
        assert report.mismatches == 0
        assert report.leak_failures == 0
        assert report.completed + report.typed_errors == 10
        # the SIGKILLed replica was resurrected by the supervisor
        assert report.supervisor_restarts >= 1, report.to_dict()
        # the engine.step burst forced at least one engine
        # resurrection on a surviving replica
        assert report.engine_restarts >= 1, report.to_dict()
        assert report.replicas_checked == 2

    @pytest.mark.slow
    def test_chaos_inprogram_inner_loop(self):
        """r22 chaos lane: one replica armed with ``--multi-step 4
        --speculate 4 --prefill-chunk 8`` (fault sites UNCHANGED) —
        the engine.step burst forces a resurrection that rebuilds the
        in-program spec/chunk engine and replays onto it. Typed
        termination everywhere, zero leaks, clean ledger reconcile,
        bit-identical successes vs the vanilla in-process oracle."""
        chaos = _load_chaos()
        report = chaos.run_chaos(
            replicas=1, requests=8, seed=0, kill_replica=False,
            extra_server_args=["--multi-step", "4",
                               "--speculate", "4",
                               "--prefill-chunk", "8"])
        assert report.ok, report.to_dict()
        assert report.hangs == 0
        assert report.mismatches == 0
        assert report.leak_failures == 0
        assert report.ledger_failures == 0
        assert report.completed + report.typed_errors == 8
        # the burst really resurrected the in-program engine
        assert report.engine_restarts >= 1, report.to_dict()
        assert report.replicas_checked == 1

    @pytest.mark.slow
    def test_chaos_soak(self):
        """Soak variant: more requests, hotter fault schedule, a second
        seed — the invariants must hold wherever the schedule lands."""
        chaos = _load_chaos()
        report = chaos.run_chaos(
            replicas=2, requests=24, seed=7,
            replica_faults=("engine.step:at=4|5|6,p=0.01,max=9;"
                            "alloc.page:p=0.08,max=6;"
                            "net.recv:p=0.04,max=4"),
            router_fault_p=0.1, router_fault_max=5,
            kill_replica=True)
        assert report.ok, report.to_dict()
        assert report.engine_restarts >= 1
        assert report.supervisor_restarts >= 1
        assert report.replicas_checked == 2
