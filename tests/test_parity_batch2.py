"""Behavior tests for the second parity batch: vision transforms/datasets/
ops, text datasets, distributed tail (split/new_group/entries/spawn/data
generators/role makers), regularizer, device/sysconfig/hub/incubate,
inference tail.
"""

import os

import numpy as np
import pytest

import paddle_tpu as pt


# -- vision.transforms --------------------------------------------------------

def test_functional_flips_crops_pad():
    import paddle_tpu.vision.transforms as T
    img = (np.random.default_rng(0).random((8, 10, 3)) * 255).astype(
        "uint8")
    assert np.array_equal(T.hflip(T.hflip(img)), img)
    assert np.array_equal(T.vflip(T.vflip(img)), img)
    assert T.crop(img, 1, 2, 3, 4).shape == (3, 4, 3)
    assert T.center_crop(img, 4).shape == (4, 4, 3)
    assert T.pad(img, 2).shape == (12, 14, 3)
    assert T.pad(img, (1, 2, 3, 4)).shape == (8 + 2 + 4, 10 + 1 + 3, 3)
    # short-edge resize from an int size
    assert T.resize(img, 4).shape == (4, 5, 3)


def test_functional_rotate():
    import paddle_tpu.vision.transforms as T
    img = np.zeros((6, 6), "float32")
    img[0, :] = 1.0  # top row
    r = T.rotate(img, 90)  # counter-clockwise: top row -> left column
    assert r.shape == (6, 6)
    assert r[:, 0].sum() > r[:, -1].sum()
    e = T.rotate(np.ones((4, 8), "float32"), 90, expand=True)
    assert e.shape == (8, 4)


def test_functional_color_adjust():
    import paddle_tpu.vision.transforms as T
    img = (np.random.default_rng(1).random((6, 6, 3)) * 255).astype(
        "uint8")
    assert np.array_equal(T.adjust_brightness(img, 1.0), img)
    dark = T.adjust_brightness(img, 0.5)
    assert dark.mean() < img.mean()
    # hue round-trip at zero shift (within uint8 rounding)
    h0 = T.adjust_hue(img, 0.0)
    assert np.abs(h0.astype(int) - img.astype(int)).max() <= 1
    assert np.abs(T.adjust_hue(img, 0.3).astype(int) -
                  img.astype(int)).max() > 2
    g = T.to_grayscale(img)
    assert g.shape == (6, 6, 1)
    assert T.to_grayscale(img, 3).shape == (6, 6, 3)
    c = T.adjust_contrast(img, 1.5)
    assert c.shape == img.shape


def test_transform_classes():
    import paddle_tpu.vision.transforms as T
    img = (np.random.default_rng(2).random((16, 16, 3)) * 255).astype(
        "uint8")
    assert T.ColorJitter(0.2, 0.2, 0.2, 0.1)(img).shape == img.shape
    assert T.Grayscale(3)(img).shape == (16, 16, 3)
    assert T.Pad(2)(img).shape == (20, 20, 3)
    out = T.RandomResizedCrop(8)(img)
    assert out.shape[:2] == (8, 8)
    assert T.RandomRotation(10)(img).shape == img.shape
    with pytest.raises(ValueError):
        T.HueTransform(0.7)


# -- vision datasets / backend / ops -----------------------------------------

@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    from PIL import Image
    root = tmp_path_factory.mktemp("imgs")
    for cls in ("a", "b"):
        d = root / cls
        d.mkdir()
        for i in range(2):
            arr = (np.random.default_rng(i).random((8, 8, 3)) * 255
                   ).astype("uint8")
            Image.fromarray(arr).save(str(d / f"{cls}{i}.png"))
    return str(root)


def test_dataset_folder(image_dir):
    import paddle_tpu.vision as V
    df = V.datasets.DatasetFolder(image_dir)
    assert len(df) == 4 and df.classes == ["a", "b"]
    img, label = df[0]
    assert img.shape == (8, 8, 3) and label == 0
    imf = V.datasets.ImageFolder(image_dir)
    assert len(imf) == 4 and imf[0][0].shape == (8, 8, 3)


def test_flowers_voc_synthetic():
    import paddle_tpu.vision as V
    fl = V.datasets.Flowers(mode="test")
    img, label = fl[1]
    assert img.shape == (64, 64, 3) and 0 <= int(label) < 102
    voc = V.datasets.VOC2012(mode="train")
    img, mask = voc[0]
    assert img.shape == (96, 96, 3) and mask.shape == (96, 96)
    assert int(mask.max()) < 21


def test_image_backend_and_ops(image_dir):
    import paddle_tpu.vision as V
    assert V.get_image_backend() == "pil"
    with pytest.raises(ValueError):
        V.set_image_backend("magick")
    path = os.path.join(image_dir, "a", "a0.png")
    arr = np.asarray(V.image_load(path))
    assert arr.shape == (8, 8, 3)
    raw = V.ops.read_file(path)
    assert raw.numpy().dtype == np.uint8
    # decode via PIL handles png too
    dec = V.ops.decode_jpeg(raw, mode="rgb")
    assert tuple(dec.shape) == (3, 8, 8)


def test_vision_ops_yolo_and_deform():
    import paddle_tpu.vision.ops as ops
    rng = np.random.default_rng(3)
    x = pt.to_tensor(rng.standard_normal((1, 12, 4, 4)).astype("float32"))
    img_size = pt.to_tensor(np.array([[32, 32]], "int32"))
    boxes, scores = ops.yolo_box(x, img_size, [10, 13, 16, 30], 1, 0.01,
                                 8)
    assert boxes.shape[-1] == 4
    xc = pt.to_tensor(rng.standard_normal((1, 3, 6, 6)).astype("float32"))
    offset = pt.to_tensor(np.zeros((1, 2 * 9, 6, 6), "float32"))
    w = pt.to_tensor(rng.standard_normal((4, 3, 3, 3)).astype("float32"))
    out = ops.deform_conv2d(xc, offset, w, padding=1)
    assert tuple(out.shape) == (1, 4, 6, 6)


# -- text datasets ------------------------------------------------------------

def test_text_datasets_shapes():
    import paddle_tpu.text as T
    uh = T.UCIHousing(mode="train")
    f, p = uh[0]
    assert f.shape == (13,) and p.shape == (1,)
    ng = T.Imikolov(data_type="NGRAM", window_size=5)
    assert len(ng[0]) == 5
    sq = T.Imikolov(data_type="SEQ")
    src, trg = sq[0]
    assert src.shape == trg.shape
    ml = T.Movielens()
    s = ml[0]
    assert len(s) == 8 and s[-1].dtype == np.float32
    co = T.Conll05st()
    wid, pred, mark, labels = co[0]
    assert wid.shape == mark.shape == labels.shape
    assert mark[int(pred)] == 1
    for cls in (T.WMT14, T.WMT16):
        src, trg_in, trg_next = cls()[0]
        assert trg_in.shape == trg_next.shape
        assert trg_in[0] == 2  # <bos>


def test_uci_housing_learnable():
    """The synthetic corpus must be learnable (linear model fits)."""
    import paddle_tpu.text as T
    uh = T.UCIHousing(mode="train")
    X = np.stack([s[0] for s in uh.samples])
    y = np.stack([s[1] for s in uh.samples])[:, 0]
    coef, *_ = np.linalg.lstsq(
        np.concatenate([X, np.ones((len(X), 1), "float32")], axis=1), y,
        rcond=None)
    resid = y - np.concatenate(
        [X, np.ones((len(X), 1), "float32")], axis=1) @ coef
    assert np.abs(resid).mean() < 0.5


# -- distributed tail ---------------------------------------------------------

def test_new_group_wait_entries():
    import paddle_tpu.distributed as dist
    g = dist.new_group([0, 1], axis_name="mp")
    assert g.nranks == 2 and g.get_group_rank(1) == 1
    assert g.get_group_rank(7) == -1
    t = pt.to_tensor(np.ones(3, "float32"))
    dist.wait(t)  # must not raise
    pe = dist.ProbabilityEntry(1.0)
    assert pe.admit(0)
    assert dist.ProbabilityEntry(0.0).admit(0) is False
    cf = dist.CountFilterEntry(3)
    assert not cf.admit(2) and cf.admit(3)
    assert "count_filter" in cf._to_attr()
    with pytest.raises(ValueError):
        dist.ProbabilityEntry(1.5)


def test_split_linear_and_embedding():
    import paddle_tpu.distributed as dist
    x = pt.to_tensor(np.random.default_rng(4).standard_normal(
        (2, 6)).astype("float32"))
    out = dist.split(x, (6, 8), operation="linear", axis=1)
    assert tuple(out.shape) == (2, 8)
    out = dist.split(x, (6, 8), operation="linear", axis=0)
    assert tuple(out.shape) == (2, 8)
    ids = pt.to_tensor(np.array([[1, 2]], "int64"))
    emb = dist.split(ids, (16, 4), operation="embedding")
    assert tuple(emb.shape) == (1, 2, 4)
    with pytest.raises(ValueError):
        dist.split(x, (6, 8), operation="conv")


def test_fleet_class_and_role_makers(monkeypatch):
    import paddle_tpu.distributed.fleet as fleet
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    rm = fleet.PaddleCloudRoleMaker()
    assert rm.worker_index() == 2 and rm.worker_num() == 4
    assert rm.is_worker() and not rm.is_server()
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    assert fleet.PaddleCloudRoleMaker().is_server()
    udf = fleet.UserDefinedRoleMaker(current_id=1, role=fleet.Role.SERVER,
                                     worker_num=3,
                                     server_endpoints=["127.0.0.1:1"])
    assert udf.is_server() and udf.get_pserver_endpoints()
    f = fleet.Fleet()
    assert hasattr(f, "distributed_optimizer")
    assert fleet.CommunicateTopology is not None


def test_multi_slot_data_generator_roundtrip():
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.io.heavy_dataset import parse_slot_line

    class Gen(fleet.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def g():
                yield [("ids", [1, 2, 3]), ("label", [1])]
            return g

    out = Gen().run_from_memory(["x"])
    assert out == ["ids:1 2 3;label:1"]
    parsed = parse_slot_line(out[0])
    assert parsed["ids"].tolist() == [1, 2, 3]
    assert parsed["label"].tolist() == [1]


# -- regularizer --------------------------------------------------------------

def test_l1_l2_decay_in_optimizer():
    import paddle_tpu.optimizer as opt
    from paddle_tpu.regularizer import L1Decay, L2Decay
    w = pt.to_tensor(np.array([2.0, -2.0], "float32"))
    w.stop_gradient = False
    p = pt.Parameter(w.value)
    # L2: update = lr*(g + coeff*w); with g=0, w shrinks toward 0
    sgd = opt.SGD(learning_rate=0.1, parameters=[p],
                  weight_decay=L2Decay(0.5))
    p.grad = pt.Tensor(np.zeros(2, "float32"))
    sgd.step()
    np.testing.assert_allclose(p.numpy(), [1.9, -1.9], rtol=1e-6)
    # L1: update = lr*coeff*sign(w): equal magnitude shift
    p2 = pt.Parameter(np.array([2.0, -0.5], "float32"))
    sgd2 = opt.SGD(learning_rate=0.1, parameters=[p2],
                   weight_decay=L1Decay(0.5))
    p2.grad = pt.Tensor(np.zeros(2, "float32"))
    sgd2.step()
    np.testing.assert_allclose(p2.numpy(), [1.95, -0.45], rtol=1e-6)


# -- small modules ------------------------------------------------------------

def test_device_module():
    import paddle_tpu.device as device
    assert device.get_cudnn_version() is None
    assert not device.is_compiled_with_cuda()
    assert device.is_compiled_with_tpu()
    assert device.XPUPlace is not None


def test_sysconfig_paths_exist():
    import paddle_tpu.sysconfig as sysconfig
    assert os.path.isdir(sysconfig.get_include())
    assert os.path.isdir(sysconfig.get_lib())
    assert os.path.exists(os.path.join(sysconfig.get_include(),
                                       "pt_custom_op.h"))


def test_hub_local_repo(tmp_path):
    import paddle_tpu.hub as hub
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['numpy']\n"
        "def toy(scale=2):\n"
        "    'doubles the scale'\n"
        "    return scale * 2\n")
    repo = str(tmp_path)
    assert hub.list(repo, source="local") == ["toy"]
    assert "doubles" in hub.help(repo, "toy", source="local")
    assert hub.load(repo, "toy", source="local", scale=5) == 10
    with pytest.raises(RuntimeError):
        hub.list("user/repo", source="github")


def test_incubate_and_onnx():
    import paddle_tpu.incubate as incubate
    assert incubate.LookAhead is not None
    assert incubate.ModelAverage is not None
    import jax.numpy as jnp
    s = incubate.segment_sum(jnp.ones((4, 2)), jnp.array([0, 0, 1, 1]),
                             num_segments=2)
    np.testing.assert_allclose(np.asarray(s), [[2, 2], [2, 2]])
    import paddle_tpu.onnx as onnx
    from paddle_tpu.core.enforce import InvalidArgumentError
    with pytest.raises(InvalidArgumentError):
        onnx.export(None, "/tmp/x")


def test_inference_tail():
    import paddle_tpu.inference as inf
    assert inf.get_num_bytes_of_data_type(inf.DataType.FLOAT32) == 4
    assert inf.get_num_bytes_of_data_type(inf.DataType.BFLOAT16) == 2
    assert "paddle_tpu" in inf.get_version()
    assert inf.PlaceType.TPU == 4
    assert inf.Tensor is not None and inf.PredictorPool is not None


def test_fleet_meta_parallel_namespace():
    import paddle_tpu.distributed.fleet as fleet
    mp = fleet.meta_parallel
    for n in ("VocabParallelEmbedding", "ColumnParallelLinear",
              "RowParallelLinear", "ParallelCrossEntropy", "LayerDesc",
              "SharedLayerDesc", "PipelineLayer",
              "get_rng_state_tracker"):
        assert hasattr(mp, n), n
    mp.model_parallel_random_seed(11)
    tracker = mp.get_rng_state_tracker()
    with tracker.rng_state("global_seed"):
        a = pt.randn([2]).numpy()
    with tracker.rng_state("global_seed"):
        b = pt.randn([2]).numpy()
    assert not np.array_equal(a, b)  # stream advances
    assert hasattr(fleet.utils, "recompute")
    assert hasattr(fleet.utils, "fused_allreduce_gradients")


def test_signature_compat_calls():
    """Reference-style keyword calls that used to TypeError."""
    import paddle_tpu.nn.functional as F
    a = pt.to_tensor(np.array([True, False]))
    o = pt.to_tensor(np.array([False, False]))
    assert pt.logical_or(a, a, out=o) is o
    m = F.sequence_mask(x=pt.to_tensor(np.array([1, 3])), maxlen=4)
    assert tuple(np.asarray(m.value).shape) == (2, 4)
    import paddle_tpu.distributed as dist
    dist.all_reduce(pt.to_tensor(np.ones(2, "float32")),
                    use_calc_stream=False)
    w = pt.to_tensor(np.random.default_rng(0).standard_normal(
        (3, 4, 2, 2)).astype("float32"))
    x = pt.to_tensor(np.random.default_rng(1).standard_normal(
        (1, 3, 4, 4)).astype("float32"))
    out = F.conv2d_transpose(x, w, stride=2, output_size=(8, 8))
    assert tuple(out.shape)[2:] == (8, 8)
    correct = pt.to_tensor(np.zeros((), "int64"))
    total = pt.to_tensor(np.zeros((), "int64"))
    from paddle_tpu.metric import accuracy
    accuracy(pt.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8]], "float32")),
             pt.to_tensor(np.array([[0], [1]])), correct=correct,
             total=total)
    assert int(correct.numpy()) == 2 and int(total.numpy()) == 2
