#!/usr/bin/env Rscript
# paddle_tpu inference from R (reference parity: r/example/mobilenet.r).
# Usage: Rscript predict.R <model_prefix>

library(reticulate)

args <- commandArgs(trailingOnly = TRUE)
prefix <- if (length(args) >= 1) args[[1]] else "model"

inference <- import("paddle_tpu.inference")
np <- import("numpy")

config <- inference$Config(prefix)
config$enable_memory_optim()
predictor <- inference$create_predictor(config)

input_names <- predictor$get_input_names()
h <- predictor$get_input_handle(input_names[[1]])
h$copy_from_cpu(np$random$rand(1L, 3L, 224L, 224L)$astype("float32"))

predictor$run()

out_names <- predictor$get_output_names()
out <- predictor$get_output_handle(out_names[[1]])
result <- out$copy_to_cpu()
cat("output shape:", paste(dim(result), collapse = "x"), "\n")
