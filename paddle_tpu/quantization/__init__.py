"""Quantization: QAT (fake-quant) + PTQ (calibration).

Reference parity: python/paddle/fluid/contrib/slim/quantization/
(quantization_pass.py program rewrite, imperative QAT
imperative/qat.py ImperativeQuantAware, PTQ calibration). TPU-native:
instead of a graph-rewrite pass, QAT swaps Linear/Conv2D layers for
quant-aware wrappers (straight-through fake-quant in the eager/jit graph);
PTQ observes activation ranges on calibration batches and produces int8
weights + scales for the serving path (int8 matmuls hit the MXU via
XLA's native int8 dot support).
"""

from .quant import (FakeQuantLayer, ImperativeQuantAware, PTQ,
                    QuantConfig, QuantizedConv2D, QuantizedLinear,
                    fake_quant, quant_dequant)
