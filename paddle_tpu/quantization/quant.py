"""Quantization primitives and QAT/PTQ drivers."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import dispatch
from ..nn.common import Linear
from ..nn.conv import Conv2D
from ..nn.layer import Layer
from ..tensor import Tensor

F = dispatch.wrapped_ops


@dataclasses.dataclass
class QuantConfig:
    weight_bits: int = 8
    activation_bits: int = 8
    weight_quantize_type: str = "channel_wise_abs_max"
    activation_quantize_type: str = "moving_average_abs_max"
    moving_rate: float = 0.9
    quantizable_layer_type: tuple = ("Linear", "Conv2D")


def fake_quant(x, scale, bits: int = 8):
    """Symmetric fake-quant with straight-through estimator
    (reference: fake_quantize_op kernels). Dispatched through the op layer
    so the eager tape records the STE gradient."""
    qmax = float(2 ** (bits - 1) - 1)

    def _fq(v, s):
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
        fq = q * s / qmax
        return v + jax.lax.stop_gradient(fq - v)

    return dispatch.call_fn(_fq, "fake_quant", True, (x, scale), {})


def quant_dequant(x, bits: int = 8, axis: Optional[int] = None):
    """Quantize to int8 + dequant scales (the PTQ conversion step)."""
    raw = np.asarray(x.value if isinstance(x, Tensor) else x)
    qmax = float(2 ** (bits - 1) - 1)
    if axis is None:
        scale = np.abs(raw).max()
        q = np.clip(np.round(raw / max(scale, 1e-8) * qmax), -qmax,
                    qmax).astype(np.int8)
        return q, np.float32(scale)
    mv = np.moveaxis(raw, axis, 0)
    scale = np.abs(mv.reshape(mv.shape[0], -1)).max(axis=1)
    q = np.clip(np.round(mv / np.maximum(scale, 1e-8)[
        (slice(None),) + (None,) * (mv.ndim - 1)] * qmax), -qmax,
        qmax).astype(np.int8)
    return np.moveaxis(q, 0, axis), scale.astype(np.float32)


class FakeQuantLayer(Layer):
    """Observes activation abs-max (moving average) and fake-quants."""

    def __init__(self, bits: int = 8, moving_rate: float = 0.9):
        super().__init__()
        self.bits = bits
        self.moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.ones(())))
        self._initialized = False

    def forward(self, x):
        if self.training:
            cur = F["max"](F["abs"](x.detach() if isinstance(x, Tensor)
                                    else x))
            cur_v = cur.value if isinstance(cur, Tensor) else cur
            if not self._initialized:
                self.scale.set_value(cur_v)
                self._initialized = True
            else:
                self.scale.set_value(self.moving_rate * self.scale.value +
                                     (1 - self.moving_rate) * cur_v)
        return fake_quant(x, self.scale, self.bits)


class QuantizedLinear(Layer):
    """Linear with fake-quant on weight (per-channel) + activation."""

    def __init__(self, inner: Linear, config: QuantConfig):
        super().__init__()
        self.inner = inner
        self.act_quant = FakeQuantLayer(config.activation_bits,
                                        config.moving_rate)
        self.w_bits = config.weight_bits
        self.per_channel = "channel" in config.weight_quantize_type

    def _w_scale(self):
        w = self.inner.weight
        if self.per_channel:
            s = F["max"](F["abs"](w.detach()), axis=0, keepdim=True)
        else:
            s = F["max"](F["abs"](w.detach()))
        return s

    def forward(self, x):
        x = self.act_quant(x)
        wq = fake_quant(self.inner.weight, self._w_scale(), self.w_bits)
        return F["linear"](x, wq, self.inner.bias)


class QuantizedConv2D(Layer):
    def __init__(self, inner: Conv2D, config: QuantConfig):
        super().__init__()
        self.inner = inner
        self.act_quant = FakeQuantLayer(config.activation_bits,
                                        config.moving_rate)
        self.w_bits = config.weight_bits

    def forward(self, x):
        x = self.act_quant(x)
        w = self.inner.weight
        s = F["max"](F["abs"](w.detach()))
        wq = fake_quant(w, s, self.w_bits)
        return F["conv2d"](x, wq, self.inner.bias, self.inner._stride,
                           self.inner._padding, self.inner._dilation,
                           self.inner._groups, self.inner._data_format)


class ImperativeQuantAware:
    """QAT driver (reference: slim ImperativeQuantAware.quantize — swaps
    quantizable layers for quant-aware versions in place)."""

    def __init__(self, config: Optional[QuantConfig] = None, **kw):
        self.config = config or QuantConfig(**kw)

    def quantize(self, model: Layer) -> Layer:
        self._convert(model)
        return model

    def _convert(self, layer: Layer) -> None:
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, Linear):
                layer._sub_layers[name] = QuantizedLinear(sub, self.config)
            elif isinstance(sub, Conv2D) and type(sub) is Conv2D:
                layer._sub_layers[name] = QuantizedConv2D(sub, self.config)
            else:
                self._convert(sub)

    def save_quantized_model(self, model: Layer, path: str,
                             input_spec=None) -> None:
        from ..static.program import build_program
        model.eval()
        prog = build_program(model, input_spec)
        prog.save(path)


class PTQ:
    """Post-training quantization: run calibration batches through
    observers, then export int8 weights + scales
    (reference: slim PostTrainingQuantization)."""

    def __init__(self, bits: int = 8):
        self.bits = bits
        self.act_ranges: Dict[str, float] = {}
        self._hooks = []

    def _observer(self, name):
        def hook(layer, inputs, outputs):
            x = inputs[0]
            v = float(np.abs(np.asarray(
                x.value if isinstance(x, Tensor) else x)).max())
            self.act_ranges[name] = max(self.act_ranges.get(name, 0.0), v)
        return hook

    def calibrate(self, model: Layer, data_iter, num_batches: int = 8
                  ) -> None:
        model.eval()
        for name, sub in model.named_sublayers():
            if isinstance(sub, (Linear, Conv2D)):
                self._hooks.append(
                    sub.register_forward_post_hook(self._observer(name)))
        for i, batch in enumerate(data_iter):
            if i >= num_batches:
                break
            xs = batch[0] if isinstance(batch, (list, tuple)) else batch
            model(xs if isinstance(xs, Tensor) else Tensor(
                jnp.asarray(np.asarray(xs))))
        for h in self._hooks:
            h.remove()
        self._hooks.clear()

    def quantize_weights(self, model: Layer) -> Dict[str, dict]:
        """Return {layer_name: {weight_int8, weight_scale, act_scale}}."""
        out = {}
        for name, sub in model.named_sublayers():
            if isinstance(sub, Linear):
                q, s = quant_dequant(sub.weight, self.bits, axis=1)
                out[name] = {"weight_int8": q, "weight_scale": s,
                             "act_scale": self.act_ranges.get(name)}
            elif isinstance(sub, Conv2D):
                q, s = quant_dequant(sub.weight, self.bits, axis=0)
                out[name] = {"weight_int8": q, "weight_scale": s,
                             "act_scale": self.act_ranges.get(name)}
        return out


# --------------------------------------------------------------------------
# int8 EXECUTION path (reference: slim quantization_pass.py rewrites the
# program for quantized inference; trt_int8_calibrator.cc feeds TensorRT
# int8 engines). TPU-native: weights stored as int8 arrays, activations
# quantized on the fly, and the matmul/conv runs as an int8 x int8 ->
# int32 XLA dot/conv (the MXU's native int8 path) with one scale-multiply
# to come back to float.
# --------------------------------------------------------------------------

def quantize_int8(x, scale):
    """Symmetric rounding quantization to int8 (execution-path analog of
    the reference's quantize_op): q = clip(round(x / scale * 127))."""
    raw = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    s = jnp.maximum(jnp.asarray(scale), 1e-8)
    return jnp.clip(jnp.round(raw / s * 127.0), -127, 127).astype(jnp.int8)


def dequantize_int8(q, scale):
    """reference dequantize_op: float = q * scale / 127."""
    raw = q.value if isinstance(q, Tensor) else jnp.asarray(q)
    return raw.astype(jnp.float32) * (jnp.asarray(scale) / 127.0)


class Int8Linear(Layer):
    """Linear executing as int8 x int8 -> int32 on the MXU.

    Weight is held as an int8 buffer with a per-output-channel scale;
    the activation quantizes against the calibrated abs-max. One float
    multiply recovers the result scale — XLA fuses it into the dot's
    epilogue."""

    def __init__(self, inner: Linear, act_scale: float, bits: int = 8):
        super().__init__()
        assert bits == 8, "int8 execution supports 8-bit only"
        q, w_scale = quant_dequant(inner.weight, bits, axis=1)
        self.register_buffer("weight_int8", Tensor(jnp.asarray(q)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(w_scale)))  # [out]
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(np.float32(act_scale))))
        self.bias = inner.bias

    def forward(self, x):
        def kernel(xv, wq, ws, asc, *maybe_bias):
            qx = quantize_int8(xv, asc)
            acc = jax.lax.dot_general(
                qx, wq, (((qx.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (
                (asc / 127.0) * (ws / 127.0))
            if maybe_bias:
                out = out + maybe_bias[0]
            return out

        args = [x, self.weight_int8, self.weight_scale, self.act_scale]
        if self.bias is not None:
            args.append(self.bias)
        return dispatch.call_fn(kernel, "int8_linear", False,
                                tuple(args), {})


class Int8Conv2D(Layer):
    """Conv2D executing as int8 x int8 -> int32 (per-tensor weight
    scale; NCHW)."""

    def __init__(self, inner: Conv2D, act_scale: float, bits: int = 8):
        super().__init__()
        assert bits == 8
        q, w_scale = quant_dequant(inner.weight, bits, axis=None)
        self.register_buffer("weight_int8", Tensor(jnp.asarray(q)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(np.float32(w_scale))))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(np.float32(act_scale))))
        self.bias = inner.bias
        self._stride = inner._stride
        self._padding = inner._padding
        self._dilation = inner._dilation
        self._groups = inner._groups
        self._data_format = inner._data_format

    def forward(self, x):
        # same stride/padding/dilation normalization as the fp32 conv2d
        # kernel (ops/nn_functional.py) so both paths accept identical
        # configs
        from ..ops.nn_functional import _conv_padding, _norm_tuple
        stride, padding = self._stride, self._padding
        dilation, groups = self._dilation, self._groups

        def kernel(xv, wq, ws, asc, *maybe_bias):
            qx = quantize_int8(xv, asc)
            acc = jax.lax.conv_general_dilated(
                qx, wq, window_strides=_norm_tuple(stride, 2),
                padding=_conv_padding(padding, 2, stride, dilation,
                                      wq.shape[2:]),
                rhs_dilation=_norm_tuple(dilation, 2),
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (
                (asc / 127.0) * (ws / 127.0))
            if maybe_bias:
                out = out + maybe_bias[0].reshape(1, -1, 1, 1)
            return out

        args = [x, self.weight_int8, self.weight_scale, self.act_scale]
        if self.bias is not None:
            args.append(self.bias)
        return dispatch.call_fn(kernel, "int8_conv2d", False,
                                tuple(args), {})


# symmetric-quantization ranges shared by the KV compute path (the
# in-VMEM kernel dequant, ops/pallas/paged_attention.py), the paged
# pool append (models/gpt.py paged_kv_append) and the r23 spill/wire
# blob codecs (serving/prefix_cache.py pack_page_blob): ONE definition
# so "deq = q * s / qmax" means the same thing in every tier a page
# visits — device, host blob, disk blob, wire
KV_QMAX_INT8 = 127.0
KV_QMAX_INT4 = 7.0


def quantize_kv(x, eps: float = 1e-8):
    """Symmetric int8 quantization for KV-cache tokens: per-(token,
    head) abs-max over the head_dim axis — the finest granularity that
    stays outside the attention contractions, so one scale multiply per
    page row recovers the values (deq = q * s / 127, the same
    convention as quantize_int8/dequantize_int8 above). Returns
    ``(int8 values [..., H, D], float32 scales [..., H])``. Used by the
    paged KV cache (models/gpt.py PagedKVCache int8 mode), where
    halving KV bytes directly halves the dominant decode-step HBM
    category (PROFILE_DECODE.json: 5.5 GB/step of KV at b128)."""
    raw = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    s = jnp.maximum(jnp.max(jnp.abs(raw.astype(jnp.float32)), axis=-1),
                    eps)
    q = jnp.clip(jnp.round(raw.astype(jnp.float32) / s[..., None]
                           * KV_QMAX_INT8),
                 -KV_QMAX_INT8, KV_QMAX_INT8).astype(jnp.int8)
    return q, s.astype(jnp.float32)


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of quantize_kv: deq = q * scale / 127."""
    raw = q.value if isinstance(q, Tensor) else jnp.asarray(q)
    s = scale.value if isinstance(scale, Tensor) else jnp.asarray(scale)
    return (raw.astype(jnp.float32) *
            (s.astype(jnp.float32) / KV_QMAX_INT8)[..., None]
            ).astype(dtype)


# --------------------------------------------------------------------------
# Host-lane KV blob codecs (r23, serving/prefix_cache.py pack_page_blob).
# Pure numpy: these run on the engine's HOST thread against page blocks
# already copied off-device (spill, fetch_pages, drain handoff), so
# they must not touch jax. The math is PINNED to the device-side
# convention above — quantize_kv_np(x) is bit-equal to quantize_kv(x)
# on float32 input (tests/test_kv_substrate.py), and decode is exactly
# deq = q * s / qmax, the same formula the Ragged Paged Attention
# kernel applies in-VMEM. int4 packs two values per byte along
# head_dim (low nibble first, ceil(D/2) bytes per row).
# --------------------------------------------------------------------------

def quantize_kv_np(x: np.ndarray, eps: float = 1e-8):
    """Numpy twin of :func:`quantize_kv`: per-(token, head) abs-max
    over the last axis, ``q = clip(round(x / s * 127))`` int8, scales
    float32. Returns ``(q [..., H, D], s [..., H])``."""
    raw = np.asarray(x, np.float32)
    s = np.maximum(np.max(np.abs(raw), axis=-1), eps).astype(np.float32)
    q = np.clip(np.round(raw / s[..., None] * KV_QMAX_INT8),
                -KV_QMAX_INT8, KV_QMAX_INT8).astype(np.int8)
    return q, s


def dequantize_kv_np(q: np.ndarray, scale: np.ndarray,
                     dtype=np.float32) -> np.ndarray:
    """Numpy twin of :func:`dequantize_kv`: deq = q * s / 127."""
    return (np.asarray(q, np.float32) *
            (np.asarray(scale, np.float32) / KV_QMAX_INT8)[..., None]
            ).astype(dtype)


def quantize_kv_int4_np(x: np.ndarray, eps: float = 1e-8):
    """Symmetric int4 KV quantization (host lane): per-(token, head)
    abs-max scales like int8, ``q = clip(round(x / s * 7), -7, 7)``,
    two nibbles packed per byte along head_dim (low nibble = even
    index; odd head_dim zero-pads the final high nibble). Returns
    ``(packed uint8 [..., H, ceil(D/2)], s float32 [..., H])``."""
    raw = np.asarray(x, np.float32)
    s = np.maximum(np.max(np.abs(raw), axis=-1), eps).astype(np.float32)
    q = np.clip(np.round(raw / s[..., None] * KV_QMAX_INT4),
                -KV_QMAX_INT4, KV_QMAX_INT4).astype(np.int8)
    d = q.shape[-1]
    if d % 2:
        q = np.concatenate(
            [q, np.zeros(q.shape[:-1] + (1,), np.int8)], axis=-1)
    # two's-complement nibbles: q & 0xF maps [-7, 7] into [0, 15]
    lo = (q[..., 0::2].astype(np.uint8)) & 0x0F
    hi = (q[..., 1::2].astype(np.uint8)) & 0x0F
    return (lo | (hi << 4)).astype(np.uint8), s


def dequantize_kv_int4_np(packed: np.ndarray, scale: np.ndarray,
                          head_dim: int, dtype=np.float32) -> np.ndarray:
    """Inverse of :func:`quantize_kv_int4_np`: unpack nibbles
    (sign-extended), deq = q * s / 7, truncated back to ``head_dim``."""
    p = np.asarray(packed, np.uint8)
    lo = (p & 0x0F).astype(np.int8)
    hi = ((p >> 4) & 0x0F).astype(np.int8)
    # sign-extend 4-bit two's complement
    lo = np.where(lo > 7, lo - 16, lo)
    hi = np.where(hi > 7, hi - 16, hi)
    q = np.empty(p.shape[:-1] + (p.shape[-1] * 2,), np.int8)
    q[..., 0::2] = lo
    q[..., 1::2] = hi
    q = q[..., :head_dim]
    return (q.astype(np.float32) *
            (np.asarray(scale, np.float32) / KV_QMAX_INT4)[..., None]
            ).astype(dtype)


class WeightOnlyInt8Linear(Layer):
    """Weight-ONLY int8 linear for decode/serving, where weight
    STREAMING is the bottleneck (PROFILE_DECODE.json roofline: at small
    per-step batch the matmuls are bandwidth-bound on the weights, so
    halving weight bytes approaches 2x tokens/s; activations carry
    negligible traffic and stay bf16/f32 — the reference analog is
    TensorRT's weight-only int8 engines, trt_int8_calibrator.cc
    capability). No calibration needed: only weights quantize
    (per-out-channel abs-max), the dot runs in the activation dtype and
    the per-column scale applies to the OUTPUT (x @ deq(W) ==
    (x @ W_q) * s — one [*, out] multiply XLA fuses into the dot
    epilogue, keeping the int8->bf16 convert inside the dot's operand
    read instead of materializing a dequantized copy)."""

    def __init__(self, inner):
        super().__init__()
        q, s = quant_dequant(inner.weight, 8, axis=1)
        self.register_buffer("weight_int8", Tensor(jnp.asarray(q)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(s, dtype=jnp.float32)))
        self.bias = getattr(inner, "bias", None)
        self.in_features = inner.weight.shape[0]
        self.out_features = inner.weight.shape[1]

    def forward(self, x):
        def kernel(xv, wq, ws, *maybe_bias):
            qmax = 127.0
            acc = jax.lax.dot_general(
                xv, wq.astype(xv.dtype),
                (((xv.ndim - 1,), (0,)), ((), ())))
            out = acc * (ws.astype(xv.dtype) / qmax)
            if maybe_bias:
                out = out + maybe_bias[0].astype(out.dtype)
            return out

        args = [x, self.weight_int8, self.weight_scale]
        if self.bias is not None:
            args.append(self.bias)
        return dispatch.call_fn(kernel, "weight_only_int8_linear", False,
                                tuple(args), {})


def convert_to_weight_only_int8(model: Layer, extra_types=()) -> int:
    """Swap every [in, out]-weighted linear-like layer for a
    WeightOnlyInt8Linear IN PLACE; returns the number converted. By
    default covers nn.Linear plus the tensor-parallel linears (their
    single-chip forward is the same x @ W (+ b)); embeddings and norms
    stay float. For decode this halves the streamed weight bytes —
    the dominant cost per generated token.

    Tensor-parallel layers keep their sharding: the original weight
    pspec is propagated onto the int8 buffer (quantization is
    per-out-channel, so the layout is unchanged) and the per-column
    scale gets the weight's axis-1 spec. Under mp_degree > 1 a warning
    is still emitted — the converted layer no longer applies the
    original layer's activation constraints (gather_output /
    input_is_parallel plumbing), so verify the partitioner's choices."""
    import warnings

    from jax.sharding import PartitionSpec as P

    from ..distributed.mp_layers import (ColumnParallelLinear,
                                         RowParallelLinear)
    from ..distributed.topology import get_hybrid_communicate_group
    types = (Linear, ColumnParallelLinear, RowParallelLinear,
             *extra_types)
    hcg = get_hybrid_communicate_group()
    mp_degree = hcg.get_model_parallel_world_size() if hcg else 1
    count = 0

    def convert(layer: Layer) -> None:
        nonlocal count
        for name, sub in list(layer._sub_layers.items()):
            if type(sub) in types:
                pspec = getattr(sub.weight, "pspec", None)
                if mp_degree > 1 and pspec is not None:
                    warnings.warn(
                        f"convert_to_weight_only_int8: converting "
                        f"{type(sub).__name__} {name!r} under "
                        f"mp_degree={mp_degree}; the weight pspec "
                        f"{pspec} is propagated to the int8 buffer but "
                        "the original layer's activation constraints "
                        "are dropped — check the resulting sharding",
                        UserWarning, stacklevel=3)
                new = WeightOnlyInt8Linear(sub)
                if pspec is not None:
                    # quantized per-out-channel: same [in, out] layout,
                    # so the weight spec carries over; the [out] scale
                    # follows the weight's out axis
                    new.weight_int8.pspec = pspec
                    new.weight_int8.is_distributed = True
                    out_axis = pspec[1] if len(pspec) > 1 else None
                    new.weight_scale.pspec = P(out_axis)
                    new.weight_scale.is_distributed = True
                layer._sub_layers[name] = new
                count += 1
            else:
                convert(sub)

    convert(model)
    return count


def convert_to_int8(model: Layer, ptq: "PTQ") -> Layer:
    """Swap calibrated Linear/Conv2D layers for int8-executing versions
    (reference: quantization_pass.py program rewrite). The model must
    have been run through ptq.calibrate() first."""
    from ..core.enforce import InvalidArgumentError

    def convert(layer: Layer, prefix: str = "") -> None:
        for name, sub in list(layer._sub_layers.items()):
            full = f"{prefix}.{name}" if prefix else name
            act = ptq.act_ranges.get(full)
            if type(sub) is Linear or type(sub) is Conv2D:
                if act is None:
                    raise InvalidArgumentError(
                        f"no calibration range for layer {full!r}; run "
                        "PTQ.calibrate() over representative data first")
                if type(sub) is Conv2D:
                    if sub._data_format not in ("NCHW", None):
                        raise InvalidArgumentError(
                            f"int8 conversion of layer {full!r}: only "
                            "NCHW Conv2D is supported (got "
                            f"{sub._data_format!r})")
                    layer._sub_layers[name] = Int8Conv2D(sub, act)
                else:
                    layer._sub_layers[name] = Int8Linear(sub, act)
            else:
                convert(sub, full)

    convert(model)
    return model
