"""Eager autograd engine (the dygraph tape).

TPU-native equivalent of the reference's imperative autograd
(reference: paddle/fluid/imperative/basic_engine.cc:39 Init, :305 Execute;
gradient accumulation gradient_accumulator.cc; tracer.cc:207
CreateGradOpNode). Each eager op records a GradNode holding the jax.vjp
pullback of its pure-functional kernel; ``backward`` walks the node graph in
reverse topological order, accumulating cotangents and depositing leaf
gradients into Tensor.grad.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


class _TLS(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.guard_stack = []


_tls = _TLS()


def is_grad_enabled() -> bool:
    return _tls.grad_enabled


def set_grad_enabled(mode: bool) -> None:
    _tls.grad_enabled = bool(mode)


class no_grad:  # noqa: N801 - reference API name
    """Disable grad recording — usable as a context manager OR a
    decorator (reference: paddle.no_grad(func) wraps func). The decorator
    path returns a plain function so instance methods bind ``self``
    normally; the context path keeps a stack of prior states so one
    instance nests safely."""

    def __new__(cls, func=None):
        if func is not None:
            import functools
            import inspect

            if inspect.isgeneratorfunction(func):
                # Hold the guard across iteration, not just generator
                # creation (reference decorates generator functions the
                # same way, fluid/dygraph/base.py _decorate_function).
                # Full delegation: send()/throw()/return-value all pass
                # through; only the inner generator's advances run with
                # grad disabled.
                @functools.wraps(func)
                def wrapper(*args, **kwargs):
                    gen = func(*args, **kwargs)
                    try:
                        with no_grad():
                            item = next(gen)
                        while True:
                            try:
                                sent = yield item
                            except GeneratorExit:
                                with no_grad():
                                    gen.close()
                                raise
                            except BaseException as exc:
                                with no_grad():
                                    item = gen.throw(exc)
                            else:
                                with no_grad():
                                    item = gen.send(sent)
                    except StopIteration as stop:
                        return stop.value
            else:
                @functools.wraps(func)
                def wrapper(*args, **kwargs):
                    with no_grad():
                        return func(*args, **kwargs)
            return wrapper
        return super().__new__(cls)

    def __call__(self, *args, **kwargs):
        # @paddle.no_grad() decorator-instance form (reference-valid)
        if len(args) == 1 and not kwargs and callable(args[0]):
            return no_grad(args[0])
        raise TypeError("no_grad() context instance is not callable")

    def __enter__(self):
        # The saved-state stack lives in thread-local storage (not on the
        # instance): one shared instance stays correct across threads and
        # nested re-entry.
        _tls.guard_stack.append(_tls.grad_enabled)
        _tls.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = _tls.guard_stack.pop()
        return False


@contextlib.contextmanager
def enable_grad():
    prev = _tls.grad_enabled
    _tls.grad_enabled = True
    try:
        yield
    finally:
        _tls.grad_enabled = prev


class GradNode:
    """One recorded op: pullback + wiring to input tensors."""

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "out_tree",
                 "out_tensors", "_cotangents")

    def __init__(self, name: str, vjp_fn: Callable,
                 inputs: Sequence["Any"], out_avals: List[Any],
                 out_tree=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # Tensors corresponding to vjp args
        self.out_avals = out_avals  # jax.ShapeDtypeStruct per output leaf
        self.out_tree = out_tree    # treedef of the kernel's output
        self.out_tensors: List[Any] = []  # weak-ish refs for hooks
        self._cotangents: Optional[List[Any]] = None

    def add_cotangent(self, index: int, value) -> None:
        if self._cotangents is None:
            self._cotangents = [None] * len(self.out_avals)
        cur = self._cotangents[index]
        self._cotangents[index] = value if cur is None else cur + value

    def materialize_cotangents(self) -> List[Any]:
        cots = self._cotangents or [None] * len(self.out_avals)
        out = []
        for aval, c in zip(self.out_avals, cots):
            if c is not None:
                out.append(c)
            elif jax.dtypes.issubdtype(aval.dtype, np.inexact):
                out.append(jax.numpy.zeros(aval.shape, aval.dtype))
            else:
                out.append(np.zeros(aval.shape, jax.dtypes.float0))
        return out


def _toposort(roots: List[GradNode]) -> List[GradNode]:
    order: List[GradNode] = []
    visited = set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t is not None and t.grad_node is not None:
                stack.append((t.grad_node, False))
    return order  # reverse-topological (outputs last -> we walk reversed)


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             grad_sink: Optional[Dict[int, Any]] = None) -> None:
    """Run reverse-mode accumulation from ``tensors``.

    Matches reference semantics: Tensor.backward() seeds with ones for
    scalar outputs (python/paddle/fluid/dygraph/varbase_patch_methods.py:169).
    """
    from ..tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor) or not isinstance(
            grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    def _deposit(t, g):
        if grad_sink is not None:
            cur = grad_sink.get(id(t))
            grad_sink[id(t)] = g if cur is None else cur + g
        else:
            t._accumulate_grad(g)

    roots: List[GradNode] = []
    for t, g in zip(tensors, grad_tensors):
        if t.grad_node is None:
            # Leaf with requires-grad: d t/d t = seed directly.
            if not t.stop_gradient:
                seed = _seed_for(t, g)
                _deposit(t, seed)
            continue
        seed = _seed_for(t, g)
        t.grad_node.add_cotangent(t._out_index, seed)
        roots.append(t.grad_node)

    order = _toposort(roots)
    for node in reversed(order):
        cots = node.materialize_cotangents()
        if node.out_tree is not None:
            arg = jax.tree_util.tree_unflatten(node.out_tree, cots)
        else:
            arg = cots[0] if len(cots) == 1 else tuple(cots)
        in_grads = node.vjp_fn(arg)
        for t, g in zip(node.inputs, in_grads):
            if t is None or g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            for hook in t._grad_hooks:
                res = hook(g)
                if res is not None:
                    g = res
            if t.grad_node is not None and not t.is_leaf:
                t.grad_node.add_cotangent(t._out_index, g)
                if t._retain_grads:
                    _deposit(t, g)
            elif not t.stop_gradient:
                _deposit(t, g)
        node._cotangents = None
        if not retain_graph:
            node.vjp_fn = _used_up
            node.inputs = []


def _used_up(*_a, **_k):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True if needed.")


def _seed_for(t, g):
    import jax.numpy as jnp
    if g is None:
        return jnp.ones(t.shape, dtype=t.dtype)
    from ..tensor import Tensor
    return g.value if isinstance(g, Tensor) else jax.numpy.asarray(g)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """Functional-style paddle.grad over the eager tape (reference:
    imperative/partial_grad_engine.cc). Returns grads w.r.t. ``inputs``
    without touching .grad fields."""
    from ..tensor import Tensor

    single = isinstance(inputs, Tensor)
    inputs_list = [inputs] if single else list(inputs)
    saved = [(t._retain_grads, t.stop_gradient) for t in inputs_list]
    for t in inputs_list:
        t._retain_grads = True
        t.stop_gradient = False
    sink: Dict[int, Any] = {}
    try:
        backward(outputs, grad_outputs, retain_graph=retain_graph,
                 grad_sink=sink)
        results = []
        for t in inputs_list:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"Input tensor {t.name or t} was not used in graph")
                results.append(None)
            else:
                results.append(Tensor(g, stop_gradient=True))
    finally:
        for t, (r, sg) in zip(inputs_list, saved):
            t._retain_grads = r
            t.stop_gradient = sg
    return results[0] if single else results
