"""Eager autograd engine (the dygraph tape).

TPU-native equivalent of the reference's imperative autograd
(reference: paddle/fluid/imperative/basic_engine.cc:39 Init, :305 Execute;
gradient accumulation gradient_accumulator.cc; tracer.cc:207
CreateGradOpNode). Each eager op records a GradNode holding the jax.vjp
pullback of its pure-functional kernel; ``backward`` walks the node graph in
reverse topological order, accumulating cotangents and depositing leaf
gradients into Tensor.grad.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


class _TLS(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.guard_stack = []


_tls = _TLS()


def is_grad_enabled() -> bool:
    return _tls.grad_enabled


def set_grad_enabled(mode: bool) -> None:
    _tls.grad_enabled = bool(mode)


class no_grad:  # noqa: N801 - reference API name
    """Disable grad recording — usable as a context manager OR a
    decorator (reference: paddle.no_grad(func) wraps func). The decorator
    path returns a plain function so instance methods bind ``self``
    normally; the context path keeps a stack of prior states so one
    instance nests safely."""

    def __new__(cls, func=None):
        if func is not None:
            import functools
            import inspect

            if inspect.isgeneratorfunction(func):
                # Hold the guard across iteration, not just generator
                # creation (reference decorates generator functions the
                # same way, fluid/dygraph/base.py _decorate_function).
                # Full delegation: send()/throw()/return-value all pass
                # through; only the inner generator's advances run with
                # grad disabled.
                @functools.wraps(func)
                def wrapper(*args, **kwargs):
                    gen = func(*args, **kwargs)
                    try:
                        with no_grad():
                            item = next(gen)
                        while True:
                            try:
                                sent = yield item
                            except GeneratorExit:
                                with no_grad():
                                    gen.close()
                                raise
                            except BaseException as exc:
                                with no_grad():
                                    item = gen.throw(exc)
                            else:
                                with no_grad():
                                    item = gen.send(sent)
                    except StopIteration as stop:
                        return stop.value
            else:
                @functools.wraps(func)
                def wrapper(*args, **kwargs):
                    with no_grad():
                        return func(*args, **kwargs)
            return wrapper
        return super().__new__(cls)

    def __call__(self, *args, **kwargs):
        # @paddle.no_grad() decorator-instance form (reference-valid)
        if len(args) == 1 and not kwargs and callable(args[0]):
            return no_grad(args[0])
        raise TypeError("no_grad() context instance is not callable")

    def __enter__(self):
        # The saved-state stack lives in thread-local storage (not on the
        # instance): one shared instance stays correct across threads and
        # nested re-entry.
        _tls.guard_stack.append(_tls.grad_enabled)
        _tls.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _tls.grad_enabled = _tls.guard_stack.pop()
        return False


@contextlib.contextmanager
def enable_grad():
    prev = _tls.grad_enabled
    _tls.grad_enabled = True
    try:
        yield
    finally:
        _tls.grad_enabled = prev


class GradNode:
    """One recorded op: pullback + wiring to input tensors.

    ``fwd_fn`` (set by dispatch) is the closed-over pure forward whose
    jax.vjp produced ``vjp_fn``; under ``create_graph=True`` the engine
    re-dispatches the pullback as a differentiable kernel built from it,
    so the backward pass is itself taped (reference double-grad:
    python/paddle/fluid/dygraph/base.py:440 plus the *_grad_grad kernels
    in mul_op.cc / conv_op.h / activation_op.cu / batch_norm_op.cc — here
    second order falls out of vjp-of-vjp, no per-op double-grad kernels).
    ``taped_vjp`` marks nodes (PyLayer) whose vjp_fn can run in Tensor
    mode via ``vjp_fn(cots, taped=True)``.
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "out_tree",
                 "out_tensors", "_cotangents", "fwd_fn", "taped_vjp")

    def __init__(self, name: str, vjp_fn: Callable,
                 inputs: Sequence["Any"], out_avals: List[Any],
                 out_tree=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # Tensors corresponding to vjp args
        self.out_avals = out_avals  # jax.ShapeDtypeStruct per output leaf
        self.out_tree = out_tree    # treedef of the kernel's output
        self.out_tensors: List[Any] = []  # weak-ish refs for hooks
        self._cotangents: Optional[List[Any]] = None
        self.fwd_fn: Optional[Callable] = None
        self.taped_vjp = False

    def add_cotangent(self, index: int, value) -> None:
        if self._cotangents is None:
            self._cotangents = [None] * len(self.out_avals)
        cur = self._cotangents[index]
        self._cotangents[index] = value if cur is None \
            else _taped_add(cur, value)

    def materialize_cotangents(self) -> List[Any]:
        cots = self._cotangents or [None] * len(self.out_avals)
        out = []
        for aval, c in zip(self.out_avals, cots):
            if c is not None:
                out.append(c)
            elif jax.dtypes.issubdtype(aval.dtype, np.inexact):
                out.append(jax.numpy.zeros(aval.shape, aval.dtype))
            else:
                out.append(np.zeros(aval.shape, jax.dtypes.float0))
        return out


def _taped_add(cur, value):
    """Accumulate two cotangents. Under create_graph one side may be a
    taped Tensor: keep the Tensor operand on the left so the add goes
    through taped dispatch (a raw jax.Array.__add__ would coerce the
    Tensor via __jax_array__ and silently drop its history)."""
    from ..tensor import Tensor as _T
    if not isinstance(cur, _T) and isinstance(value, _T):
        cur, value = value, cur
    return cur + value


def _toposort(roots: List[GradNode]) -> List[GradNode]:
    order: List[GradNode] = []
    visited = set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t is not None and t.grad_node is not None:
                stack.append((t.grad_node, False))
    return order  # reverse-topological (outputs last -> we walk reversed)


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             grad_sink: Optional[Dict[int, Any]] = None,
             create_graph: bool = False) -> None:
    """Run reverse-mode accumulation from ``tensors``.

    Matches reference semantics: Tensor.backward() seeds with ones for
    scalar outputs (python/paddle/fluid/dygraph/varbase_patch_methods.py:169).
    With ``create_graph=True`` every pullback is re-dispatched as a taped
    op, so the produced gradients are themselves differentiable.
    """
    from ..tensor import Tensor  # local import to avoid cycle

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor) or not isinstance(
            grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    def _deposit(t, g):
        if grad_sink is not None:
            cur = grad_sink.get(id(t))
            grad_sink[id(t)] = g if cur is None else _taped_add(cur, g)
        else:
            t._accumulate_grad(g)

    roots: List[GradNode] = []
    for t, g in zip(tensors, grad_tensors):
        if t.grad_node is None:
            # Leaf with requires-grad: d t/d t = seed directly.
            if not t.stop_gradient:
                seed = _seed_for(t, g, keep_tensor=create_graph)
                _deposit(t, seed)
            continue
        seed = _seed_for(t, g, keep_tensor=create_graph)
        t.grad_node.add_cotangent(t._out_index, seed)
        roots.append(t.grad_node)

    order = _toposort(roots)
    # create_graph builds the double-grad graph regardless of the
    # enclosing grad mode (reference dygraph does too): the re-dispatched
    # pullbacks must record even inside a no_grad() block.
    grad_mode = enable_grad() if create_graph else contextlib.nullcontext()
    with grad_mode:
        for node in reversed(order):
            cots = node.materialize_cotangents()
            if node.out_tree is not None:
                arg = jax.tree_util.tree_unflatten(node.out_tree, cots)
            else:
                arg = cots[0] if len(cots) == 1 else tuple(cots)
            if create_graph:
                in_grads = _taped_pullback(node, arg)
            else:
                in_grads = node.vjp_fn(arg)
            for t, g in zip(node.inputs, in_grads):
                if t is None or g is None:
                    continue
                if getattr(g, "dtype", None) == jax.dtypes.float0:
                    continue
                for hook in t._grad_hooks:
                    res = hook(g)
                    if res is not None:
                        g = res
                if t.grad_node is not None and not t.is_leaf:
                    t.grad_node.add_cotangent(t._out_index, g)
                    if t._retain_grads:
                        _deposit(t, g)
                elif not t.stop_gradient:
                    _deposit(t, g)
            node._cotangents = None
            if not retain_graph:
                node.vjp_fn = _used_up
                node.fwd_fn = None
                node.inputs = []


def _taped_pullback(node: GradNode, cot_tree):
    """Run ``node``'s pullback through eager dispatch so the backward
    computation is recorded on the tape (double-grad support).

    The dispatched kernel re-derives the pullback from the node's closed
    forward: grads = vjp(fwd)(cot). jax differentiates vjp-of-vjp, so
    second (and higher) order falls out without per-op grad-grad kernels
    (reference ships those by hand: mul_op.cc MulDoubleGrad et al.)."""
    from .. import dispatch

    if node.fwd_fn is not None:
        fwd = node.fwd_fn

        def kernel(cot, *primals):
            _, pullback = jax.vjp(fwd, *primals)
            return pullback(cot)

        return dispatch.call_fn(kernel, node.name + "_grad", True,
                                (cot_tree, *node.inputs), {})
    if node.taped_vjp:
        return node.vjp_fn(cot_tree, taped=True)
    if node.vjp_fn is _used_up:
        _used_up()
    raise RuntimeError(
        f"create_graph=True cannot differentiate through op "
        f"'{node.name}': its GradNode records no re-traceable forward")


def _used_up(*_a, **_k):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True if needed.")


def _seed_for(t, g, keep_tensor: bool = False):
    import jax.numpy as jnp
    if g is None:
        return jnp.ones(t.shape, dtype=t.dtype)
    from ..tensor import Tensor
    if isinstance(g, Tensor):
        # Under create_graph keep the seed taped: grad_outputs may carry
        # its own history (chained higher-order graphs).
        return g if keep_tensor else g.value
    return jax.numpy.asarray(g)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Functional-style paddle.grad over the eager tape (reference:
    imperative/partial_grad_engine.cc; create_graph arg
    python/paddle/fluid/dygraph/base.py:411,440). Returns grads w.r.t.
    ``inputs`` without touching .grad fields. With ``create_graph=True``
    the returned grads are taped and can be differentiated again."""
    from ..tensor import Tensor

    if not only_inputs:
        raise NotImplementedError(
            "only_inputs=False is not supported (the reference dygraph "
            "engine rejects it too, dygraph/base.py:548)")
    if retain_graph is None:
        retain_graph = create_graph

    single = isinstance(inputs, Tensor)
    inputs_list = [inputs] if single else list(inputs)
    ng_list = []
    if no_grad_vars is not None:
        ng_list = ([no_grad_vars] if isinstance(no_grad_vars, Tensor)
                   else list(no_grad_vars))
    # Capture ALL original flags before any mutation: a tensor listed in
    # both inputs and no_grad_vars must restore to its pre-call state no
    # matter the restore order.
    saved = [(t._retain_grads, t.stop_gradient) for t in inputs_list]
    ng_saved = [t.stop_gradient for t in ng_list]
    for t in inputs_list:
        t._retain_grads = True
        t.stop_gradient = False
    for t in ng_list:
        t.stop_gradient = True
    sink: Dict[int, Any] = {}
    try:
        backward(outputs, grad_outputs, retain_graph=retain_graph,
                 grad_sink=sink, create_graph=create_graph)
        results = []
        for t in inputs_list:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"Input tensor {t.name or t} was not used in graph")
                results.append(None)
            elif isinstance(g, Tensor):
                results.append(g)
            else:
                results.append(Tensor(g, stop_gradient=not create_graph))
    finally:
        for t, (r, sg) in zip(inputs_list, saved):
            t._retain_grads = r
            t.stop_gradient = sg
        for t, sg in zip(ng_list, ng_saved):
            t.stop_gradient = sg
    return results[0] if single else results
