"""Autograd: eager tape engine + PyLayer custom-function escape hatch."""

from .engine import (GradNode, backward, enable_grad, grad, is_grad_enabled,
                     no_grad, set_grad_enabled)
from .py_layer import PyLayer, PyLayerContext
