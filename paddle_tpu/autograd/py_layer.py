"""PyLayer: user-defined forward/backward.

Reference parity: python/paddle/autograd/py_layer.py:192 PyLayer (used by
fleet recompute and custom ops). Static-mode analog is jax.custom_vjp; the
eager tape records the user's backward directly.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp

from ..autograd.engine import GradNode, is_grad_enabled
from ..tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved: Tuple = ()
        self.extras: dict = {}

    def save_for_backward(self, *tensors) -> None:
        self._saved = tensors

    def saved_tensor(self) -> Tuple:
        return self._saved

    saved_tensors = property(lambda self: self._saved)


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    """Subclass with @staticmethod forward(ctx, *args) and
    backward(ctx, *grads); call via .apply(*args)."""

    @staticmethod
    def forward(ctx: PyLayerContext, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx: PyLayerContext, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        record = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args)
        out = cls.forward(ctx, *args, **kwargs)
        is_tuple = isinstance(out, (tuple, list))
        out_list = list(out) if is_tuple else [out]
        out_list = [o if isinstance(o, Tensor) else Tensor(jnp.asarray(o))
                    for o in out_list]
        if record:
            diff_inputs = [t for t in tensor_args if not t.stop_gradient]
            avals = [jax.ShapeDtypeStruct(o.shape, o.dtype)
                     for o in out_list]

            def vjp_fn(cotangents, taped=False):
                cots = cotangents if isinstance(cotangents, tuple) else \
                    (cotangents,)
                # taped (create_graph) mode: incoming cotangents may be
                # Tensors carrying history — keep them so the user's
                # backward (paddle ops) records onto the tape and the
                # produced grads stay differentiable.
                cots_t = [c if isinstance(c, Tensor) else Tensor(c)
                          for c in cots]
                grads = cls.backward(ctx, *cots_t)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                if taped:
                    raw = list(grads)
                else:
                    raw = [g.value if isinstance(g, Tensor) else g
                           for g in grads]
                # align with diff inputs (paddle: one grad per fwd input)
                if len(raw) > len(diff_inputs):
                    pos = [i for i, a in enumerate(args)
                           if isinstance(a, Tensor) and
                           not a.stop_gradient]
                    tensor_pos = [i for i, a in enumerate(args)
                                  if isinstance(a, Tensor)]
                    raw = [raw[tensor_pos.index(i)] if i in tensor_pos
                           else None for i in pos]
                return raw[:len(diff_inputs)]

            node = GradNode(cls.__name__, vjp_fn, diff_inputs, avals,
                            out_tree=None)
            node.taped_vjp = True  # backward() may run it in Tensor mode
            # out_tree None -> engine passes tuple(cots) for multi-output
            for i, o in enumerate(out_list):
                o.stop_gradient = False
                o.grad_node = node
                o._out_index = i
                node.out_tensors.append(o)
        return tuple(out_list) if is_tuple else out_list[0]


def custom_vjp_from_pylayer(cls):
    """Convert a PyLayer into a jax.custom_vjp function usable in traced
    code."""

    @jax.custom_vjp
    def fn(*args):
        ctx = PyLayerContext()
        out = cls.forward(ctx, *[Tensor(a) for a in args])
        return jax.tree_util.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    def fwd(*args):
        ctx = PyLayerContext()
        out = cls.forward(ctx, *[Tensor(a) for a in args])
        raw = jax.tree_util.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))
        saved = tuple(t.value if isinstance(t, Tensor) else t
                      for t in ctx.saved_tensor())
        return raw, saved

    def bwd(saved, g):
        ctx = PyLayerContext()
        ctx.save_for_backward(*[Tensor(s) for s in saved])
        gs = g if isinstance(g, tuple) else (g,)
        grads = cls.backward(ctx, *[Tensor(x) for x in gs])
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        return tuple(x.value if isinstance(x, Tensor) else x
                     for x in grads)

    fn.defvjp(fwd, bwd)
    return fn
