"""Program/Executor facade over traced XLA computations."""

from __future__ import annotations

import contextlib
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.enforce import InvalidArgumentError
from ..io.collate import default_collate_fn
from ..nn.layer import Layer, functional_state, functional_call
from ..tensor import Tensor


class InputSpec:
    """Symbolic input description (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def to_sds(self) -> jax.ShapeDtypeStruct:
        shape = tuple(1 if (s is None or s == -1) else s
                      for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, " \
               f"name={self.name!r})"

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, t.dtype, name)


class Program:
    """A traced computation + its parameter state.

    Reference analog: ProgramDesc (the serialized program) + its scope of
    persistable variables. ``fn(params, *inputs) -> outputs`` is pure; the
    serialized form is a StableHLO artifact from jax.export.
    """

    def __init__(self, fn: Callable, input_specs: Sequence[InputSpec],
                 params: Optional[Dict[str, Any]] = None,
                 name: str = "main"):
        self.fn = fn
        self.input_specs = list(input_specs)
        self.params = dict(params or {})
        self.name = name
        self._jitted = jax.jit(fn)
        self._exported = None

    # -- execution ------------------------------------------------------------

    def run(self, *inputs):
        raw = [i.value if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        return self._jitted(self.params, *raw)

    # -- introspection (Program surface) -------------------------------------

    def lowered_text(self) -> str:
        args = [s.to_sds() for s in self.input_specs]
        return jax.jit(self.fn).lower(self.params, *args).as_text()

    def num_ops(self) -> int:
        txt = self.lowered_text()
        return sum(1 for line in txt.splitlines()
                   if "=" in line and "func.func" not in line)

    def __str__(self):
        return self.lowered_text()

    # -- serialization --------------------------------------------------------

    def export(self) -> bytes:
        from jax import export as jexport
        args = [s.to_sds() for s in self.input_specs]
        exp = jexport.export(jax.jit(self.fn))(self.params, *args)
        return exp.serialize()

    def save(self, path_prefix: str) -> None:
        d = os.path.dirname(path_prefix)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path_prefix + ".pdmodel", "wb") as f:
            f.write(self.export())
        with open(path_prefix + ".pdiparams", "wb") as f:
            pickle.dump({k: np.asarray(v) for k, v in self.params.items()},
                        f, protocol=4)
        with open(path_prefix + ".pdmeta", "wb") as f:
            pickle.dump({"input_specs": [(s.shape, str(s.dtype), s.name)
                                         for s in self.input_specs],
                         "name": self.name}, f)


class LoadedProgram:
    """Program deserialized from a .pdmodel StableHLO artifact.

    ``precision``: None/"float32" keeps the exported dtypes; "bfloat16"/
    "float16" stores floating params in low precision — the serving win
    on TPU is HBM footprint/bandwidth (f32 matmuls already run bf16
    multiplier passes on the MXU) — and casts back to the artifact's
    rigid signature dtypes at the call boundary, where XLA fuses the
    casts into the consumers.
    """

    def __init__(self, path_prefix: str, precision: Optional[str] = None):
        from jax import export as jexport
        with open(path_prefix + ".pdmodel", "rb") as f:
            self.exported = jexport.deserialize(f.read())
        with open(path_prefix + ".pdiparams", "rb") as f:
            self.params = {k: jnp.asarray(v)
                           for k, v in pickle.load(f).items()}
        with open(path_prefix + ".pdmeta", "rb") as f:
            meta = pickle.load(f)
        self.input_specs = [InputSpec(s, d, n)
                            for s, d, n in meta["input_specs"]]
        self.name = meta.get("name", "main")
        self._orig_dtypes = {k: v.dtype for k, v in self.params.items()}
        if precision in ("bfloat16", "float16"):
            low = jnp.bfloat16 if precision == "bfloat16" else jnp.float16
            self.params = {
                k: (v.astype(low)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in self.params.items()}
        exported = self.exported
        orig = self._orig_dtypes

        def call_with_signature_dtypes(params, *xs):
            restored = {k: (v.astype(orig[k]) if v.dtype != orig[k] else v)
                        for k, v in params.items()}
            return exported.call(restored, *xs)

        self._call = jax.jit(call_with_signature_dtypes)

    def run(self, *inputs):
        raw = [i.value if isinstance(i, Tensor) else jnp.asarray(i)
               for i in inputs]
        return self._call(self.params, *raw)


def build_program(layer_or_fn, input_specs: Sequence[InputSpec],
                  training: bool = False) -> Program:
    """Capture a Layer or function into a Program (the analog of building
    a ProgramDesc under program_guard + save_inference_model pruning)."""
    specs = [s if isinstance(s, InputSpec) else InputSpec(*s)
             for s in input_specs]
    if isinstance(layer_or_fn, Layer):
        layer = layer_or_fn
        layer.eval() if not training else layer.train()
        state = functional_state(layer)

        def fn(params, *inputs):
            return functional_call(
                layer, {"params": params, "buffers": state["buffers"]},
                *[Tensor(i) for i in inputs])

        return Program(fn, specs, params=state["params"],
                       name=type(layer).__name__)

    def fn(params, *inputs):
        out = layer_or_fn(*[Tensor(i) for i in inputs])
        return jax.tree_util.tree_map(
            lambda t: t.value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    return Program(fn, specs, params={})


# -- reference-compatible module-level API -----------------------------------

_default_program: Optional[Program] = None


def default_main_program():
    return _default_program


@contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """Compatibility shim: the traced path has no global graph under
    construction; yields the program for API parity."""
    global _default_program
    prev = _default_program
    _default_program = main_program
    try:
        yield main_program
    finally:
        _default_program = prev


def data(name: str, shape, dtype="float32", lod_level=0):
    """Symbolic placeholder (reference: paddle.static.data) — returns an
    InputSpec consumed by build_program."""
    return InputSpec(shape, dtype, name)


class CompiledProgram:
    """Reference-API shim: compilation happens at Program build."""

    def __init__(self, program_or_graph, build_strategy=None):
        self.program = program_or_graph


class Executor:
    """Runs Programs (reference: fluid/executor.py:475 Executor.run with
    feed/fetch). Feed keys map to input_spec names positionally when
    unnamed."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Program = None, feed: Optional[Dict] = None,
            fetch_list=None, return_numpy: bool = True):
        program = program or _default_program
        feed = feed or {}
        inputs = []
        for i, spec in enumerate(program.input_specs):
            key = spec.name or f"x{i}"
            if key in feed:
                inputs.append(feed[key])
            else:
                vals = list(feed.values())
                inputs.append(vals[i] if i < len(vals) else None)
        out = program.run(*inputs)
        leaves = jax.tree_util.tree_leaves(out)
        if return_numpy:
            leaves = [np.asarray(l) for l in leaves]
        return leaves

    # -- dataset-driven training (reference executor.py:1662
    #    train_from_dataset over framework/trainer.h trainers) --------------

    # shared with io.DataLoader — one collate implementation
    _default_collate = staticmethod(default_collate_fn)

    def _dataset_step_fn(self, program, collate_fn, train: bool,
                         fetches: Optional[Dict] = None):
        collate = collate_fn or self._default_collate

        if isinstance(program, Program):
            names = [s.name or f"x{i}"
                     for i, s in enumerate(program.input_specs)]

            def step(batch, worker_id):
                b = collate(batch)
                if isinstance(b, dict):
                    # feed by declared input name when the spec names
                    # match; never by dict insertion order
                    if all(n in b for n in names):
                        vals = [b[n] for n in names]
                    else:
                        missing = [n for n in names if n not in b]
                        raise InvalidArgumentError(
                            f"batch keys {sorted(b)} do not cover program "
                            f"inputs {names} (missing {missing}); name "
                            "the InputSpecs after the sample slots")
                else:
                    vals = list(b) if isinstance(b, (tuple, list)) else [b]
                out = program.run(*vals)
                leaves = jax.tree_util.tree_leaves(out)
                if fetches is not None:
                    fetches["last"] = [np.asarray(l) for l in leaves]
                # a scalar first output is treated as the loss; anything
                # else contributes no loss metric (pure scoring programs)
                if leaves and jnp.ndim(leaves[0]) == 0:
                    return leaves[0]
                return None
            return step

        if callable(program):  # e.g. a jitted TrainStep
            if not train and (hasattr(program, "optimizer") or
                              hasattr(program, "opt_state")):
                raise InvalidArgumentError(
                    "infer_from_dataset must not mutate state: pass a "
                    "Program or a pure callable, not a TrainStep")

            def step(batch, worker_id):
                return program(collate(batch))
            return step

        raise InvalidArgumentError(
            "train_from_dataset needs a Program or a callable step "
            f"(got {type(program).__name__})")

    def _run_dataset(self, program, dataset, thread, debug, fetch_list,
                     collate_fn, trainer, train, trainer_kwargs):
        from ..framework.trainer import TrainerFactory
        if dataset is None:
            raise InvalidArgumentError("dataset is required")
        fetches: Optional[Dict] = {} if fetch_list is not None else None
        step = self._dataset_step_fn(program, collate_fn, train=train,
                                     fetches=fetches)
        tr = TrainerFactory.create(
            trainer, step,
            thread_num=thread or getattr(dataset, "thread_num", 1) or 1,
            **trainer_kwargs)
        result = tr.run(dataset, debug=debug)
        if fetches is not None:
            result["fetches"] = fetches.get("last")
        return result

    def train_from_dataset(self, program=None, dataset=None, thread: int = 0,
                           debug: bool = False, fetch_list=None,
                           collate_fn=None, trainer: str = "MultiTrainer",
                           **trainer_kwargs):
        """Run N device workers over the dataset's channels (reference
        Executor.train_from_dataset -> trainer_factory -> MultiTrainer::Run
        over HogwildWorkers). Returns {'steps', 'avg_loss'} plus
        'fetches' (the last step's output leaves) when fetch_list is
        given."""
        return self._run_dataset(program, dataset, thread, debug,
                                 fetch_list, collate_fn, trainer, True,
                                 trainer_kwargs)

    def infer_from_dataset(self, program=None, dataset=None, thread: int = 0,
                           debug: bool = False, fetch_list=None,
                           collate_fn=None, trainer: str = "MultiTrainer",
                           **trainer_kwargs):
        """Same worker loop for pure scoring: rejects state-mutating
        TrainStep callables (reference Executor.infer_from_dataset)."""
        return self._run_dataset(program, dataset, thread, debug,
                                 fetch_list, collate_fn, trainer, False,
                                 trainer_kwargs)


def _example_input(v, rng) -> Tensor:
    """A concrete random input for a feed var (InputSpec or Tensor) —
    used to numerically verify optimization passes before export. The
    caller passes ONE rng shared across feed vars so same-shape inputs
    stay independent; integer feeds get small random ids (all-zeros
    would probe a degenerate point, e.g. only embedding row 0)."""
    if isinstance(v, Tensor):
        return v
    sds = v.to_sds() if isinstance(v, InputSpec) else \
        InputSpec.from_tensor(v).to_sds()
    npdtype = np.dtype(sds.dtype)
    if npdtype == np.bool_:
        arr = rng.integers(0, 2, sds.shape).astype(np.bool_)
    elif np.issubdtype(npdtype, np.integer):
        arr = rng.integers(0, 16, sds.shape).astype(npdtype)
    else:
        arr = rng.standard_normal(sds.shape).astype(npdtype)
    return Tensor(jnp.asarray(arr))


def save_inference_model(path_prefix: str, feed_vars, fetch_vars=None,
                         executor=None, program=None, layer=None,
                         optimize: bool = True) -> None:
    """reference: paddle.static.save_inference_model / fluid/io.py:1246.
    Accepts either a prebuilt Program or (layer, input_specs).

    ``optimize=True`` (default, matching the reference's inference
    analysis passes) runs eval-graph fusions on a COPY of the layer
    before tracing — currently conv+BN folding
    (inference/fusion.py, the conv_bn_fuse_pass analog); the caller's
    layer is never mutated."""
    if program is None:
        if layer is not None and optimize and not layer.training:
            from ..inference.fusion import (find_foldable_pairs,
                                            fold_preserves_outputs,
                                            fuse_conv_bn)
            if next(find_foldable_pairs(layer), None) is not None:
                # pay the model deepcopy only when something will fold
                import copy
                folded = copy.deepcopy(layer)
                fuse_conv_bn(folded)
                # the name-based pairing can mis-fold a pre-activation
                # block (bn before conv, equal channels): verify on
                # three independent random examples (magnitude-scaled
                # tolerance) and keep the unfused model on mismatch
                examples = [
                    [_example_input(v, np.random.default_rng(seed))
                     for v in feed_vars] for seed in (0, 1, 2)]
                if fold_preserves_outputs(layer, folded, examples):
                    layer = folded
                else:
                    import warnings
                    warnings.warn(
                        "conv+BN folding changed the model's outputs "
                        "(pre-activation topology?); exporting UNFUSED. "
                        "Pass optimize=False to silence this check.")
        specs = [v if isinstance(v, InputSpec) else InputSpec.from_tensor(v)
                 for v in feed_vars]
        program = build_program(layer, specs)
    program.save(path_prefix)


def load_inference_model(path_prefix: str, executor=None) -> LoadedProgram:
    """reference: paddle.static.load_inference_model / fluid/io.py:1459."""
    return LoadedProgram(path_prefix)
