"""paddle_tpu.static — the traced/static program path.

TPU-native equivalent of the reference's static-graph mode
(reference: ProgramDesc protobuf IR framework/framework.proto:202 + Python
Program/Block/Operator fluid/framework.py:3979 + Executor
fluid/executor.py:475 + save/load_inference_model fluid/io.py:1246,1459).

Design: a Program is a traced, lowered XLA computation. Building it is
jax.jit tracing (one compiled program replaces the op-by-op interpreter
loop); the serialized artifact is StableHLO via jax.export — the save
format replacing ProgramDesc. Autodiff on programs is jax.grad at trace
time (replacing append_backward's program-to-program transform).
"""

from .program import (CompiledProgram, Executor, InputSpec, Program,
                      build_program, data, default_main_program,
                      load_inference_model, program_guard,
                      save_inference_model)
from .api import (BuildStrategy, ExecutionStrategy, ParallelExecutor,  # noqa: E402
                  Print, Scope, Variable, WeightNormParamAttr, accuracy,
                  append_backward, auc, cpu_places, create_global_var,
                  create_parameter, cuda_places, default_startup_program,
                  deserialize_persistables, deserialize_program,
                  device_guard, global_scope, gradients, load,
                  load_from_file, load_program_state, name_scope,
                  normalize_program, py_func, save, save_to_file,
                  scope_guard, serialize_persistables, serialize_program,
                  set_program_state)
from . import nn  # noqa: E402
