"""paddle_tpu.static — the traced/static program path.

TPU-native equivalent of the reference's static-graph mode
(reference: ProgramDesc protobuf IR framework/framework.proto:202 + Python
Program/Block/Operator fluid/framework.py:3979 + Executor
fluid/executor.py:475 + save/load_inference_model fluid/io.py:1246,1459).

Design: a Program is a traced, lowered XLA computation. Building it is
jax.jit tracing (one compiled program replaces the op-by-op interpreter
loop); the serialized artifact is StableHLO via jax.export — the save
format replacing ProgramDesc. Autodiff on programs is jax.grad at trace
time (replacing append_backward's program-to-program transform).
"""

from .program import (CompiledProgram, Executor, InputSpec, Program,
                      build_program, data, default_main_program,
                      load_inference_model, program_guard,
                      save_inference_model)
