"""paddle.static.nn — static-graph op builders with auto-created weights.

Reference parity: python/paddle/static/nn/__init__.py __all__ (the
fluid/layers/nn.py builder family: fc, embedding, conv2d, batch_norm, ...).

TPU-native stance: there is no op-graph under construction — builders run
the shared functional kernels immediately (eager) or inside a trace
(build_program / @to_static capture). Parameters are created on call via
``create_parameter`` and registered in ``global_scope()`` by name; reusing
a ``ParamAttr(name=...)`` reuses the stored parameter, matching the
reference's var-name semantics. For inference-program capture the weights
freeze into the artifact — exactly what save_inference_model does in the
reference (fluid/io.py:1246 prunes + persists).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.enforce import InvalidArgumentError
from ..tensor import Parameter, Tensor
from .api import global_scope

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "case",
    "cond", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "crf_decoding", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "multi_box_head", "nce", "prelu",
    "py_func", "row_conv", "spectral_norm", "switch_case", "while_loop",
    "sparse_embedding", "sequence_conv", "sequence_softmax",
    "sequence_pool", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_slice", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_reshape", "sequence_scatter", "sequence_enumerate",
    "sequence_reverse",
]


def _param(shape, dtype, attr, is_bias=False, default_initializer=None):
    """Create-or-reuse a parameter; named params live in global_scope."""
    import paddle_tpu as pt
    name = getattr(attr, "name", None) if attr is not None else None
    if name:
        existing = global_scope().find_var(name)
        if isinstance(existing, Parameter):
            return existing
    p = pt.create_parameter(shape, dtype=dtype, name=name, attr=attr,
                            is_bias=is_bias,
                            default_initializer=default_initializer)
    if name:
        global_scope().set_var(name, p)
    return p


def _apply(name, *args, **kwargs):
    from .. import dispatch
    return dispatch.apply(name, *args, **kwargs)


def _act(x, act: Optional[str]):
    if act is None:
        return x
    return _apply(act, x)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: paddle.static.nn.fc (fluid/layers/nn.py fc)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = []
    for xi in xs:
        shp = tuple(xi.shape)
        in_dim = int(np.prod(shp[num_flatten_dims:]))
        flat = _apply("reshape", xi, (*shp[:num_flatten_dims], in_dim))
        w = _param((in_dim, size), xi.dtype, weight_attr)
        outs.append(_apply("matmul", flat, w))
    out = outs[0]
    for o in outs[1:]:
        out = _apply("add", out, o)
    if bias_attr is not False:
        b = _param((size,), out.dtype, bias_attr, is_bias=True)
        out = _apply("add", out, b)
    return _act(out, activation)


def embedding(input, size, is_sparse=False, is_distributed=False,  # noqa: A002
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: paddle.static.nn.embedding."""
    w = _param(tuple(size), convert_dtype(dtype), param_attr)
    return _apply("embedding", input, w, padding_idx)


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32"):
    """reference: paddle.static.nn.sparse_embedding — PS-backed embedding;
    collective-mode execution uses a dense table (the PS path shards via
    paddle_tpu.distributed.ps sparse tables)."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    """reference: paddle.static.nn.conv2d."""
    fs = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _param((num_filters, cin // groups, *fs), input.dtype, param_attr)
    b = None if bias_attr is False else _param(
        (num_filters,), input.dtype, bias_attr, is_bias=True)
    out = _apply("conv2d", input, w, b, stride, padding, dilation, groups,
                 data_format)
    return _act(out, act)


def conv2d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCHW"):
    """reference: paddle.static.nn.conv2d_transpose."""
    if filter_size is None:
        raise InvalidArgumentError("filter_size is required")
    fs = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _param((cin, num_filters // groups, *fs), input.dtype, param_attr)
    b = None if bias_attr is False else _param(
        (num_filters,), input.dtype, bias_attr, is_bias=True)
    out = _apply("conv2d_transpose", input, w, b, stride, padding,
                 dilation=dilation, groups=groups, data_format=data_format)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    """reference: paddle.static.nn.conv3d."""
    fs = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    w = _param((num_filters, cin // groups, *fs), input.dtype, param_attr)
    b = None if bias_attr is False else _param(
        (num_filters,), input.dtype, bias_attr, is_bias=True)
    out = _apply("conv3d", input, w, b, stride, padding, dilation, groups,
                 data_format)
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None,
                     use_cudnn=True, act=None, name=None,
                     data_format="NCDHW"):
    """reference: paddle.static.nn.conv3d_transpose."""
    fs = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    w = _param((cin, num_filters // groups, *fs), input.dtype, param_attr)
    b = None if bias_attr is False else _param(
        (num_filters,), input.dtype, bias_attr, is_bias=True)
    out = _apply("conv3d_transpose", input, w, b, stride, padding,
                 dilation=dilation, groups=groups, data_format=data_format)
    return _act(out, act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, weight_attr=None, bias_attr=None,
                  name=None):
    """reference: paddle.static.nn.deform_conv2d."""
    fs = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = x.shape[1]
    w = _param((num_filters, cin // groups, *fs), x.dtype, weight_attr)
    b = None if bias_attr is False else _param(
        (num_filters,), x.dtype, bias_attr, is_bias=True)
    return _apply("deformable_conv", x, offset, w, mask, b, stride,
                  padding, dilation, deformable_groups, groups)


def batch_norm(input, act=None, is_test=False, momentum=0.9,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", in_place=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    """reference: paddle.static.nn.batch_norm. Moving stats live in
    global_scope under their names (or auto-names) and update in-place on
    train-mode calls, matching the reference's persistable-var update."""
    from ..framework import unique_name
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    # "ones" is only the fallback — an attr.initializer still wins inside
    # resolve_initializer
    scale = _param((c,), input.dtype, param_attr,
                   default_initializer="ones")
    bias = _param((c,), input.dtype, bias_attr, is_bias=True)
    scope = global_scope()
    mname = moving_mean_name or unique_name.generate("bn_moving_mean")
    vname = moving_variance_name or unique_name.generate("bn_moving_var")
    mean = scope.find_var(mname)
    var = scope.find_var(vname)
    if mean is None:
        mean = Tensor(jnp.zeros((c,), input.dtype), stop_gradient=True,
                      name=mname)
        var = Tensor(jnp.ones((c,), input.dtype), stop_gradient=True,
                     name=vname)
        scope.set_var(mname, mean)
        scope.set_var(vname, var)
    training = not (is_test or use_global_stats)
    out, new_mean, new_var = _apply(
        "batch_norm", input, mean, var, scale, bias, training, momentum,
        epsilon, data_layout)
    if training:
        mean.set_value(new_mean)
        var.set_value(new_var)
    return _act(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: paddle.static.nn.layer_norm."""
    shp = tuple(int(s) for s in input.shape[begin_norm_axis:])
    w = _param(shp, input.dtype, param_attr,
               default_initializer="ones") if scale else None
    b = _param(shp, input.dtype, bias_attr, is_bias=True) if shift else None
    out = _apply("layer_norm", input, shp, w, b, epsilon)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    """reference: paddle.static.nn.group_norm."""
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    w = _param((c,), input.dtype, param_attr, default_initializer="ones")
    b = _param((c,), input.dtype, bias_attr, is_bias=True)
    out = _apply("group_norm", input, groups, w, b, epsilon, data_layout)
    return _act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None,  # noqa: A002
                  bias_attr=None, name=None):
    """reference: paddle.static.nn.instance_norm."""
    c = input.shape[1]
    w = None if param_attr is False else _param(
        (c,), input.dtype, param_attr, default_initializer="ones")
    b = None if bias_attr is False else _param(
        (c,), input.dtype, bias_attr, is_bias=True)
    return _apply("instance_norm", input, w, b, epsilon)


def data_norm(input, act=None, epsilon=1e-4, param_attr=None,  # noqa: A002
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """reference: paddle.static.nn.data_norm (CTR stats normalization)."""
    c = input.shape[-1]
    scope = global_scope()
    from ..framework import unique_name
    base = name or unique_name.generate("data_norm")
    names = [f"{base}.batch_size", f"{base}.batch_sum",
             f"{base}.batch_square_sum"]
    vals = [scope.find_var(n) for n in names]
    if vals[0] is None:
        vals = [Tensor(jnp.full((c,), 1e4, input.dtype), stop_gradient=True),
                Tensor(jnp.zeros((c,), input.dtype), stop_gradient=True),
                Tensor(jnp.full((c,), 1e4, input.dtype), stop_gradient=True)]
        for n, v in zip(names, vals):
            scope.set_var(n, v)
    out = _apply("data_norm", input, vals[0], vals[1], vals[2], epsilon)
    return _act(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: paddle.static.nn.spectral_norm — returns the
    spectrally-normalized weight (operators/spectral_norm_op)."""
    w = weight.value if isinstance(weight, Tensor) else jnp.asarray(weight)
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    key_u = jnp.ones((wm.shape[0],), w.dtype)
    u = key_u / (jnp.linalg.norm(key_u) + eps)
    v = None
    for _ in range(max(1, power_iters)):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wm @ v
    return Tensor(w / sigma)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: paddle.static.nn.prelu (modes: all/channel/element)."""
    if mode == "all":
        shape = (1,)
    elif mode == "channel":
        c = x.shape[1] if data_format == "NCHW" else x.shape[-1]
        shape = (c,)
    elif mode == "element":
        shape = tuple(x.shape[1:])
    else:
        raise InvalidArgumentError(f"unknown prelu mode {mode!r}")
    a = _param(shape, x.dtype, param_attr, default_initializer=0.25)
    if mode == "channel" and x.ndim > 2 and data_format == "NCHW":
        a = _apply("reshape", a, (1, -1) + (1,) * (x.ndim - 2))
    return _apply("prelu", x, a)


def row_conv(input, future_context_size, param_attr=None,  # noqa: A002
             act=None):
    """reference: paddle.static.nn.row_conv (lookahead conv)."""
    d = input.shape[-1]
    w = _param((future_context_size + 1, d), input.dtype, param_attr)
    out = _apply("row_conv", input, w)
    return _act(out, act)


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: paddle.static.nn.nce (noise-contrastive estimation)."""
    d = input.shape[-1]
    w = _param((num_total_classes, d), input.dtype, param_attr)
    b = None if bias_attr is False else _param(
        (num_total_classes,), input.dtype, bias_attr, is_bias=True)
    return _apply("nce", input, label, w, b, num_neg_samples or 10)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: paddle.static.nn.bilinear_tensor_product."""
    w = _param((size, x.shape[-1], y.shape[-1]), x.dtype, param_attr)
    b = None if bias_attr is False else _param(
        (size,), x.dtype, bias_attr, is_bias=True)
    out = _apply("bilinear_tensor_product", x, y, w, b)
    return _act(out, act)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """reference: paddle.static.nn.multi_box_head (SSD detection head,
    fluid/layers/detection.py). Builds per-feature-map loc/conf conv heads
    + prior boxes; returns (mbox_locs, mbox_confs, boxes, variances)."""
    if min_sizes is None:
        # reference formula: evenly spaced ratios of the base size
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (num_layer - 2 + 1e-9)) \
            if num_layer > 2 else 0
        min_sizes.append(base_size * 0.10)
        max_sizes.append(base_size * 0.20)
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = min_sizes[:num_layer]
        max_sizes = max_sizes[:num_layer]

    locs, confs, boxes_all, vars_all = [], [], [], []
    ih, iw = int(image.shape[2]), int(image.shape[3])
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        ms_list = ms if isinstance(ms, (list, tuple)) else [ms]
        mx = max_sizes[i] if max_sizes else None
        mx_list = (mx if isinstance(mx, (list, tuple)) else [mx]) \
            if mx is not None else None
        ar = aspect_ratios[i]
        ar_list = ar if isinstance(ar, (list, tuple)) else [ar]
        fh, fw = int(feat.shape[2]), int(feat.shape[3])
        step_i = float(steps[i]) if steps else 0.0
        boxes, variances = _apply(
            "prior_box", fh, fw, ih, iw, ms_list,
            max_sizes=mx_list or (), aspect_ratios=ar_list, flip=flip,
            clip=clip, step_w=step_i, step_h=step_i, offset=offset,
            variances=tuple(variance))
        num_priors = int(boxes.shape[2])  # [fh, fw, num_priors, 4]
        loc = conv2d(feat, num_priors * 4, kernel_size, stride=stride,
                     padding=pad, bias_attr=None)
        conf = conv2d(feat, num_priors * num_classes, kernel_size,
                      stride=stride, padding=pad, bias_attr=None)
        n = feat.shape[0]
        loc = _apply("reshape", _apply("transpose", loc, (0, 2, 3, 1)),
                     (n, -1, 4))
        conf = _apply("reshape", _apply("transpose", conf, (0, 2, 3, 1)),
                      (n, -1, num_classes))
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(_apply("reshape", boxes, (-1, 4)))
        vars_all.append(_apply("reshape", variances, (-1, 4)))
    mbox_locs = _apply("concat", locs, 1)
    mbox_confs = _apply("concat", confs, 1)
    boxes = _apply("concat", boxes_all, 0)
    variances = _apply("concat", vars_all, 0)
    return mbox_locs, mbox_confs, boxes, variances


def _wrapped(name):
    from .. import dispatch
    return dispatch.wrapped_ops[name]


def __getattr__(attr):
    # control-flow + sequence + crf_decoding re-exports share the one
    # registered kernel set (same-kernel-both-modes, like the reference's
    # AllOpKernels sharing).
    if attr in {"cond", "case", "switch_case", "while_loop"}:
        from ..ops import control_flow
        return getattr(control_flow, attr)
    _direct = {
        "crf_decoding",
        "sequence_conv", "sequence_softmax", "sequence_pool",
        "sequence_concat", "sequence_first_step", "sequence_last_step",
        "sequence_slice", "sequence_expand", "sequence_expand_as",
        "sequence_pad", "sequence_unpad", "sequence_reshape",
        "sequence_scatter", "sequence_enumerate", "sequence_reverse",
    }
    if attr in _direct:
        return _wrapped(attr)
    if attr == "py_func":
        from .api import py_func as _pf
        return _pf
    raise AttributeError(f"module 'paddle_tpu.static.nn' has no "
                         f"attribute {attr!r}")
