"""paddle.static module-level API tail.

Reference parity: python/paddle/static/__init__.py __all__ — scopes
(fluid/executor.py global_scope/scope_guard), program (de)serialization
(fluid/io.py serialize_program/save_to_file/...), program-state utilities
(fluid/io.py load_program_state/set_program_state), build/execution
strategies (framework/details/build_strategy.h:54,
execution_strategy.h), device_guard / name_scope (fluid/framework.py),
py_func (fluid/layers/nn.py py_func), append_backward / gradients
(fluid/backward.py:1363,1958).

TPU-native stance: a Program is one traced XLA computation, so several
reference knobs (BuildStrategy/ExecutionStrategy/ParallelExecutor) are
accepted-and-inert configuration shells — XLA owns scheduling and fusion.
Autodiff facades run on the eager tape (jax.vjp based) instead of
program-to-program rewriting.
"""

from __future__ import annotations

import contextlib
import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import InvalidArgumentError
from ..core.place import CPUPlace, GPUPlace, Place, TPUPlace
from ..tensor import Parameter, Tensor
from .program import Program

# Variable: in the traced world every SSA value is a Tensor.
Variable = Tensor


# -- scopes -------------------------------------------------------------------

class Scope:
    """Name -> value tree with parent lookup (reference:
    framework/scope.h). Holds persistable variables (parameters created by
    paddle.static.nn builders, global vars)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self.parent = parent

    def var(self, name: str):
        return self._vars.get(name)

    def find_var(self, name: str):
        if name in self._vars:
            return self._vars[name]
        return self.parent.find_var(name) if self.parent else None

    def set_var(self, name: str, value) -> None:
        self._vars[name] = value

    def new_scope(self) -> "Scope":
        return Scope(self)

    def local_var_names(self) -> List[str]:
        return list(self._vars)


_global_scope = Scope()
_scope_stack: List[Scope] = [_global_scope]


def global_scope() -> Scope:
    """reference: paddle.static.global_scope (fluid/executor.py)."""
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope: Scope):
    """reference: paddle.static.scope_guard (fluid/executor.py)."""
    _scope_stack.append(scope)
    try:
        yield scope
    finally:
        _scope_stack.pop()


# -- strategies / ParallelExecutor (accepted-and-inert shells) ---------------

class BuildStrategy:
    """reference: framework/details/build_strategy.h:54. XLA owns graph
    scheduling; fields are accepted for API compatibility."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = None
        self.enable_inplace = True
        self.fuse_all_optimizer_ops = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.build_cuda_graph = False
        self.debug_graphviz_path = ""


class ExecutionStrategy:
    """reference: framework/details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class ParallelExecutor:
    """reference: framework/parallel_executor.h:51 — multi-device SSA
    graph engine. Subsumed by GSPMD: the wrapped Program is already one
    sharded XLA computation; this facade keeps the call surface."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self.program = main_program
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        from .program import Executor
        return Executor().run(self.program, feed=feed,
                              fetch_list=fetch_list,
                              return_numpy=return_numpy)


# -- places -------------------------------------------------------------------

def cpu_places(device_count: Optional[int] = None) -> List[CPUPlace]:
    """reference: paddle.static.cpu_places."""
    if device_count is None:
        device_count = int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace(i) for i in range(device_count)]


def cuda_places(device_ids=None) -> List[Place]:
    """reference: paddle.static.cuda_places — here: accelerator places
    (TPU chips first, GPU otherwise)."""
    try:
        accel = [d for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        accel = []
    cls = TPUPlace if any(d.platform == "tpu" for d in accel) else GPUPlace
    if device_ids is None:
        device_ids = list(range(max(1, len(accel))))
    return [cls(i) for i in device_ids]


# -- vars ---------------------------------------------------------------------

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None) -> Tensor:
    """reference: paddle.static.create_global_var
    (fluid/layers/tensor.py)."""
    from ..core.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(shape), value, dtype=convert_dtype(dtype)),
               stop_gradient=True, name=name)
    t.persistable = persistable
    if name:
        global_scope().set_var(name, t)
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None) -> Parameter:
    """reference: paddle.static.create_parameter."""
    import paddle_tpu as pt
    p = pt.create_parameter(shape, dtype=dtype, name=name, attr=attr,
                            is_bias=is_bias,
                            default_initializer=default_initializer)
    if p.name:
        global_scope().set_var(p.name, p)
    return p


class WeightNormParamAttr:
    """reference: paddle.static.WeightNormParamAttr
    (fluid/param_attr.py WeightNormParamAttr) — ParamAttr plus the norm
    dim; consumed by nn.utils.weight_norm-style reparameterization."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


# -- context managers ---------------------------------------------------------

_device_stack: List[Optional[str]] = [None]


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    """reference: paddle.static.device_guard (fluid/framework.py) — the
    annotation PipelineOptimizer uses to split stages. Here it records the
    tag; paddle_tpu.distributed.pp consumes explicit LayerDesc lists, and
    sharding is mesh-driven, so the tag is observational."""
    _device_stack.append(device)
    try:
        yield
    finally:
        _device_stack.pop()


def current_device_tag() -> Optional[str]:
    return _device_stack[-1]


@contextlib.contextmanager
def name_scope(prefix: Optional[str] = None):
    """reference: paddle.static.name_scope — maps to jax.named_scope so
    the prefix shows up in XLA HLO metadata / profiler traces."""
    from ..framework import unique_name
    prefix = prefix or "block"
    with jax.named_scope(unique_name.generate(prefix)):
        yield


# -- debug ops ----------------------------------------------------------------

def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002,N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=False, print_phase="both"):
    """reference: paddle.static.Print (fluid/layers/control_flow.py) —
    identity that prints the value, trace-safe via jax.debug.print."""
    from jax._src import core as _jax_core
    x = input.value if isinstance(input, Tensor) else jnp.asarray(input)
    msg = message or ""
    if _jax_core.trace_state_clean():
        # eager: print directly (the axon TPU runtime has no host-callback
        # channel, so debug.print is trace-only)
        print(msg, np.asarray(x))
    else:
        jax.debug.print(msg + " {x}", x=x)
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: paddle.static.py_func (fluid/layers/nn.py) — run a host
    python function as an op. Trace-safe: lowers to jax.pure_callback; an
    optional backward_func becomes the custom vjp (host callback too)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    raw = [t.value if isinstance(t, Tensor) else jnp.asarray(t) for t in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), jnp.dtype(o.dtype))
             for o in outs]
    single_spec = specs[0] if not isinstance(out, (list, tuple)) else specs

    def host(*arrs):
        r = func(*arrs)
        rs = r if isinstance(r, (list, tuple)) else [r]
        rs = [np.asarray(v) for v in rs]
        return rs[0] if not isinstance(out, (list, tuple)) else tuple(rs)

    from jax._src import core as _jax_core
    if _jax_core.trace_state_clean() and backward_func is None:
        # eager fast path: no callback channel needed (axon TPU runtime
        # does not support host send/recv callbacks)
        res = host(*[np.asarray(r) for r in raw])
    elif backward_func is None:
        res = jax.pure_callback(host, single_spec, *raw)
    else:
        @jax.custom_vjp
        def op(*args):
            return jax.pure_callback(host, single_spec, *args)

        def fwd(*args):
            return op(*args), args

        def bwd(args, g):
            gs = g if isinstance(g, (list, tuple)) else [g]
            in_specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                             for a in args)

            def bhost(*a_and_g):
                a = a_and_g[:len(args)]
                gg = a_and_g[len(args):]
                r = backward_func(*a, *gg)
                rs = r if isinstance(r, (list, tuple)) else [r]
                return tuple(np.asarray(v) for v in rs)

            return jax.pure_callback(bhost, in_specs, *args, *gs)

        op.defvjp(fwd, bwd)
        res = op(*raw)

    wrap = lambda v: Tensor(v)  # noqa: E731
    if isinstance(out, (list, tuple)):
        return [wrap(v) for v in res]
    return wrap(res)


# -- autodiff facades ---------------------------------------------------------

def _walk_leaf_params(t: Tensor):
    """Walk the grad graph from t, yielding reachable leaf Parameters."""
    seen, out, stack = set(), [], [t]
    while stack:
        cur = stack.pop()
        if id(cur) in seen:
            continue
        seen.add(id(cur))
        if isinstance(cur, Parameter):
            out.append(cur)
        node = getattr(cur, "grad_node", None)
        if node is not None:
            stack.extend(node.inputs)
    return out


def append_backward(loss: Tensor, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference: fluid/backward.py:1363 append_backward — returns
    (param, grad) pairs. Tape-based here: runs backward from the loss and
    reads accumulated grads."""
    params = parameter_list or _walk_leaf_params(loss)
    no_grad = set(id(p) for p in (no_grad_set or []))
    loss.backward()
    return [(p, p.grad) for p in params
            if id(p) not in no_grad and p.grad is not None]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: fluid/backward.py:1958 paddle.static.gradients."""
    from ..autograd.engine import grad as _grad
    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True,
                 no_grad_vars=list(no_grad_set) if no_grad_set else None)
    return outs


# -- metrics ------------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    """reference: paddle.static.accuracy (fluid/layers/metric_op.py);
    correct/total output vars are accepted and filled when given."""
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k, correct=correct, total=total)


def auc(input, label, curve="ROC", num_thresholds=4095,  # noqa: A002
        topk=1, slide_steps=1):
    """reference: paddle.static.auc (fluid/layers/metric_op.py:257) —
    returns (auc_out, batch_auc_out, [batch_stat_pos, batch_stat_neg,
    stat_pos, stat_neg]). One-shot ROC AUC via the rank-statistic
    (Mann-Whitney) formulation; the batch AUC equals the global AUC and the
    stat vars hold the positive/negative histogram over thresholds."""
    x = input.value if isinstance(input, Tensor) else jnp.asarray(input)
    y = label.value if isinstance(label, Tensor) else jnp.asarray(label)
    score = x[:, 1] if x.ndim == 2 and x.shape[1] == 2 else x.reshape(-1)
    y = y.reshape(-1).astype(jnp.float32)
    order = jnp.argsort(score)
    ranks = jnp.empty_like(order).at[order].set(
        jnp.arange(1, score.size + 1))
    pos = jnp.sum(y)
    neg = y.size - pos
    sum_rank_pos = jnp.sum(jnp.where(y > 0, ranks.astype(jnp.float32), 0.0))
    a = (sum_rank_pos - pos * (pos + 1) / 2.0) / jnp.maximum(pos * neg, 1.0)
    auc_out = Tensor(a)
    # Threshold-bucketed stat vars, same shape contract as the reference's
    # StatPos/StatNeg ([1, num_thresholds + 1]).
    bucket = jnp.clip((score * num_thresholds).astype(jnp.int32),
                      0, num_thresholds)
    stat_pos = jnp.zeros((1, num_thresholds + 1), jnp.int32).at[
        0, bucket].add(jnp.where(y > 0, 1, 0).astype(jnp.int32))
    stat_neg = jnp.zeros((1, num_thresholds + 1), jnp.int32).at[
        0, bucket].add(jnp.where(y > 0, 0, 1).astype(jnp.int32))
    batch_auc_out = Tensor(a)
    states = [Tensor(stat_pos), Tensor(stat_neg),
              Tensor(stat_pos), Tensor(stat_neg)]
    return auc_out, batch_auc_out, states


# -- program (de)serialization ------------------------------------------------

def serialize_program(feed_vars=None, fetch_vars=None,
                      program: Program = None) -> bytes:
    """reference: paddle.static.serialize_program(feed_vars, fetch_vars)
    (static/io.py). Trace-based programs are self-contained, so the
    program itself is accepted (positionally or via ``program=``) and
    feed/fetch pruning is already done by the trace."""
    if program is None and isinstance(feed_vars, Program):
        program = feed_vars
    if not isinstance(program, Program):
        raise InvalidArgumentError(
            "serialize_program needs a Program (pass it positionally or "
            "as program=...)")
    meta = {"input_specs": [(s.shape, str(s.dtype), s.name)
                            for s in program.input_specs],
            "name": program.name}
    return pickle.dumps({"stablehlo": program.export(), "meta": meta},
                        protocol=4)


def deserialize_program(data: bytes):
    """reference: paddle.static.deserialize_program — returns the
    deserialized exported computation (callable via .call)."""
    from jax import export as jexport
    blob = pickle.loads(data)
    return jexport.deserialize(blob["stablehlo"])


def serialize_persistables(feed_vars=None, fetch_vars=None,
                           executor=None, program: Program = None) -> bytes:
    """reference: paddle.static.serialize_persistables."""
    program = program or feed_vars  # allow positional program
    if not isinstance(program, Program):
        raise InvalidArgumentError("serialize_persistables needs a Program")
    return pickle.dumps({k: np.asarray(v)
                         for k, v in program.params.items()}, protocol=4)


def deserialize_persistables(program, data: bytes, executor=None):
    """reference: paddle.static.deserialize_persistables — loads params
    back into the Program."""
    params = pickle.loads(data)
    program.params = {k: jnp.asarray(v) for k, v in params.items()}
    return program


def save_to_file(path: str, content: bytes) -> None:
    """reference: paddle.static.save_to_file."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    """reference: paddle.static.load_from_file."""
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program: Program, feed_vars=None, fetch_vars=None):
    """reference: paddle.static.normalize_program — prunes a program to
    the inference subgraph. Traced programs are already pruned (XLA DCE),
    so this is the identity."""
    return program


def save(program: Program, model_path: str, protocol: int = 4,
         **configs) -> None:
    """reference: paddle.static.save(program, model_path)
    (fluid/io.py:1840) — persist params (+ a .pdmodel next to them)."""
    program.save(model_path)


def load(program: Program, model_path: str, executor=None,
         var_list=None) -> None:
    """reference: paddle.static.load(program, model_path)
    (fluid/io.py:1948) — restore params into program."""
    with open(model_path + ".pdiparams", "rb") as f:
        params = pickle.load(f)
    program.params = {k: jnp.asarray(v) for k, v in params.items()}


def load_program_state(model_path: str, var_list=None) -> Dict[str, Any]:
    """reference: paddle.static.load_program_state."""
    with open(model_path + ".pdiparams", "rb") as f:
        return {k: np.asarray(v) for k, v in pickle.load(f).items()}


def set_program_state(program: Program, state_dict: Dict[str, Any]) -> None:
    """reference: paddle.static.set_program_state."""
    program.params = {k: jnp.asarray(v) for k, v in state_dict.items()}


def default_startup_program():
    """reference: paddle.static.default_startup_program. Initialization
    happens eagerly at parameter creation on the traced path; returns the
    (empty) startup scope holder for API parity."""
    return _startup_program


class _StartupProgram:
    """Placeholder startup program: random_seed attr is honored by
    seeding the default generator."""

    def __init__(self):
        self._seed = 0

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, s):
        self._seed = int(s)
        import paddle_tpu as pt
        pt.seed(self._seed)


_startup_program = _StartupProgram()
