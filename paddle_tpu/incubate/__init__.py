"""Incubating APIs (reference: python/paddle/incubate/__init__.py
__all__: LookAhead, ModelAverage — re-exported from the optimizer-wrapper
family, plus the segment ops the reference keeps under incubate.tensor).
"""

from ..optimizer.wrappers import Lookahead as LookAhead, ModelAverage
from ..ops.decode_extra import (segment_max, segment_mean, segment_min,
                                segment_sum)

__all__ = ["LookAhead", "ModelAverage", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]
