"""paddle_tpu.linalg — linear-algebra namespace.

Reference parity: python/paddle/linalg.py (paddle.linalg.*). Wrapped
(autograd-aware) versions of the ops/linalg.py + relevant math_extra
kernels.
"""

from . import dispatch as _dispatch
from .ops import linalg as _kernels
from .ops.registry import has_op as _has_op

_NAMES = [n for n in dir(_kernels) if not n.startswith("_")
          and callable(getattr(_kernels, n))
          and getattr(_kernels, n).__module__ == _kernels.__name__
          and _has_op(n)]
_EXTRA = [n for n in ("lu_unpack", "cdist", "block_diag", "diag_embed")
          if _has_op(n)]

for _n in _NAMES + _EXTRA:
    globals()[_n] = _dispatch.wrap_op(_n)

__all__ = sorted(set(_NAMES + _EXTRA))
del _n
