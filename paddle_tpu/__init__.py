"""paddle_tpu: a TPU-native deep learning framework.

A ground-up JAX/XLA/pjit/Pallas rebuild of fluid-era PaddlePaddle's
capabilities: eager (dygraph) module/autograd system and a traced/static
program path sharing one functional op set; optimizers/AMP/data pipeline;
Fleet-style hybrid-parallel distributed training over TPU meshes; and an
AOT inference predictor. See SURVEY.md at the repo root for the reference
structural map this build follows.
"""

__version__ = "0.1.0"

import sys as _sys

from . import core
from .core import (get_flags, set_flags, set_device, get_device,
                   set_default_dtype, seed)
from .core.dtype import (bfloat16, bool_, complex64, float16, float32,
                         float64, int16, int32, int64, int8, uint8)
from .core.place import CPUPlace, CUDAPlace, GPUPlace, Place, TPUPlace
from .tensor import Parameter, Tensor, to_tensor
from .autograd.engine import enable_grad, grad, is_grad_enabled, no_grad
from . import dispatch as _dispatch

# Publish every wrapped op at top level (paddle.add, paddle.reshape, ...).
# Names that are namespace MODULES in the reference (paddle.fft is the
# module; the transform lives at paddle.fft.fft) stay unpublished.
_module_names = {"fft", "linalg"}
_mod = _sys.modules[__name__]
for _name, _fn in _dispatch.wrapped_ops.items():
    if _name not in _module_names and not hasattr(_mod, _name):
        setattr(_mod, _name, _fn)
del _mod, _name, _fn, _module_names

# Creation aliases matching the public reference API
rand = _dispatch.wrapped_ops["rand"]
randn = _dispatch.wrapped_ops["randn"]
randint = _dispatch.wrapped_ops["randint"]
uniform = _dispatch.wrapped_ops["uniform"]
normal = _dispatch.wrapped_ops["normal"]


def __getattr__(name):
    # Lazy subpackage access: paddle_tpu.nn, paddle_tpu.optimizer, ...
    import importlib
    if name in ("nn", "optimizer", "amp", "io", "static", "jit",
                "distributed", "metric", "vision", "models", "hapi",
                "framework", "inference", "serving", "autograd", "ops",
                "profiler", "quantization", "sparsity", "text", "native",
                "distribution", "utils", "fft", "linalg", "regularizer",
                "device", "hub", "onnx", "incubate", "sysconfig"):
        return importlib.import_module(f".{name}", __name__)
    if name == "ParamAttr":  # lazy: avoids eager-importing all of nn
        from .nn.initializer import ParamAttr as _PA
        globals()["ParamAttr"] = _PA
        return _PA
    if name in ("TensorArray", "create_array", "array_write",
                "array_read", "array_length", "tensor_array_to_tensor",
                "array_to_lod_tensor", "lod_tensor_to_array"):
        from .ops import control_flow as _cf
        val = getattr(_cf, name)
        globals()[name] = val
        return val
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | {
        "nn", "optimizer", "amp", "io", "static", "jit", "distributed",
        "metric", "vision", "models", "hapi", "framework", "inference",
        "serving", "autograd", "ops", "quantization", "sparsity", "text",
        "native", "distribution", "utils", "fft", "linalg", "regularizer",
        "device", "hub", "onnx", "incubate", "sysconfig"})


def Model(*args, **kwargs):
    from .hapi import Model as _M
    return _M(*args, **kwargs)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """reference: paddle.flops (hapi/dynamic_flops.py) — exact count via
    XLA cost analysis of the traced forward."""
    from .hapi.flops import flops as _flops
    return _flops(net, input_size, custom_ops, print_detail)


def summary(net, input_size=None, dtypes=None):
    """reference: paddle.summary — per-layer parameter table (shapes are
    not traced; the table reports parameter counts)."""
    from .hapi import Model as _M
    return _M(net).summary(input_size, dtypes)


def DataParallel(*args, **kwargs):
    from .distributed.parallel import DataParallel as _DP
    return _DP(*args, **kwargs)


from .autograd.engine import set_grad_enabled  # noqa: E402


def save(obj, path, **kwargs):
    from .framework.io import save as _save
    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load
    return _load(path, **kwargs)


# -- reference-parity surface tail (paddle.* __all__ names) -------------------

from .core.dtype import (complex128, get_default_dtype,  # noqa: E402
                         convert_dtype as _convert_dtype)
from .core.place import CUDAPinnedPlace, NPUPlace, XPUPlace  # noqa: E402
from .framework.mode import (batch, check_shape, disable_static,  # noqa: E402
                             enable_static, in_dygraph_mode,
                             in_dynamic_mode, set_printoptions)

import numpy as _np  # noqa: E402

# paddle.dtype: Tensor.dtype objects are numpy dtype instances, so the
# reference's ``isinstance(x.dtype, paddle.dtype)`` idiom holds.
dtype = _np.dtype
setattr(_sys.modules[__name__], "bool", bool_)  # paddle.bool


def create_parameter(shape, dtype=None, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference: paddle.create_parameter (fluid/layers/tensor.py) — a free
    Parameter outside any Layer."""
    from .core.dtype import convert_dtype
    from .nn.initializer import resolve_initializer
    dt = convert_dtype(dtype or get_default_dtype())
    init = resolve_initializer(default_initializer, attr, is_bias)
    p = Parameter(init(tuple(shape), dt),
                  name=name or (getattr(attr, "name", None)
                                if attr is not None else None))
    if attr is not None and getattr(attr, "trainable", True) is False:
        p.trainable = False
        p.stop_gradient = True
    return p


def is_tensor(x) -> bool:
    """reference: paddle.is_tensor."""
    return isinstance(x, Tensor)


def tolist(x):
    """reference: paddle.tolist."""
    return x.tolist() if isinstance(x, Tensor) else _np.asarray(x).tolist()


def get_cuda_rng_state():
    """reference: paddle.get_cuda_rng_state — here the accelerator RNG
    state is the default generator's jax PRNG key."""
    from .core.rng import default_generator
    return [default_generator().get_state()]


def set_cuda_rng_state(state_list):
    """reference: paddle.set_cuda_rng_state."""
    from .core.rng import default_generator
    if state_list:
        default_generator().set_state(state_list[0])


def _inplace_top(name):
    def f(x, *args, **kwargs):
        return getattr(x, name)(*args, **kwargs)
    f.__name__ = name
    f.__doc__ = f"In-place variant (reference: paddle.{name})."
    return f


reshape_ = _inplace_top("reshape_")
squeeze_ = _inplace_top("squeeze_")
unsqueeze_ = _inplace_top("unsqueeze_")
scatter_ = _inplace_top("scatter_")
tanh_ = _inplace_top("tanh_")
del _inplace_top


def _with_out_param(name, unary):
    base = _dispatch.wrapped_ops[name]

    def _finish(res, out):
        if out is None:
            return res
        if not hasattr(out, "_inplace_assign"):
            raise TypeError(
                f"{name}: out= must be a paddle Tensor, got "
                f"{type(out).__name__}")
        return out._inplace_assign(res)

    if unary:
        def f(x, out=None, name=None):
            return _finish(base(x), out)
    else:
        def f(x, y, out=None, name=None):
            return _finish(base(x, y), out)
    f.__name__ = name
    f.__doc__ = (base.__doc__ or "") + \
        "\n\nAccepts the reference's ``out=`` tensor (written in place)."
    return f


# logical/bitwise ops take an optional out= tensor in the reference;
# the *_not ops are unary with out as the SECOND positional slot
for _n in ("logical_and", "logical_or", "logical_xor",
           "bitwise_and", "bitwise_or", "bitwise_xor"):
    setattr(_sys.modules[__name__], _n, _with_out_param(_n, unary=False))
for _n in ("logical_not", "bitwise_not"):
    setattr(_sys.modules[__name__], _n, _with_out_param(_n, unary=True))
del _n, _with_out_param
