"""paddle_tpu: a TPU-native deep learning framework.

A ground-up JAX/XLA/pjit/Pallas rebuild of fluid-era PaddlePaddle's
capabilities: eager (dygraph) module/autograd system and a traced/static
program path sharing one functional op set; optimizers/AMP/data pipeline;
Fleet-style hybrid-parallel distributed training over TPU meshes; and an
AOT inference predictor. See SURVEY.md at the repo root for the reference
structural map this build follows.
"""

__version__ = "0.1.0"

import sys as _sys

from . import core
from .core import (get_flags, set_flags, set_device, get_device,
                   set_default_dtype, seed)
from .core.dtype import (bfloat16, bool_, complex64, float16, float32,
                         float64, int16, int32, int64, int8, uint8)
from .core.place import CPUPlace, CUDAPlace, GPUPlace, Place, TPUPlace
from .tensor import Parameter, Tensor, to_tensor
from .autograd.engine import enable_grad, grad, is_grad_enabled, no_grad
from . import dispatch as _dispatch

# Publish every wrapped op at top level (paddle.add, paddle.reshape, ...).
# Names that are namespace MODULES in the reference (paddle.fft is the
# module; the transform lives at paddle.fft.fft) stay unpublished.
_module_names = {"fft", "linalg"}
_mod = _sys.modules[__name__]
for _name, _fn in _dispatch.wrapped_ops.items():
    if _name not in _module_names and not hasattr(_mod, _name):
        setattr(_mod, _name, _fn)
del _mod, _name, _fn, _module_names

# Creation aliases matching the public reference API
rand = _dispatch.wrapped_ops["rand"]
randn = _dispatch.wrapped_ops["randn"]
randint = _dispatch.wrapped_ops["randint"]
uniform = _dispatch.wrapped_ops["uniform"]
normal = _dispatch.wrapped_ops["normal"]


def __getattr__(name):
    # Lazy subpackage access: paddle_tpu.nn, paddle_tpu.optimizer, ...
    import importlib
    if name in ("nn", "optimizer", "amp", "io", "static", "jit",
                "distributed", "metric", "vision", "models", "hapi",
                "framework", "inference", "autograd", "ops", "profiler",
                "quantization", "sparsity", "text", "native", "distribution",
                "utils", "fft", "linalg"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | {
        "nn", "optimizer", "amp", "io", "static", "jit", "distributed",
        "metric", "vision", "models", "hapi", "framework", "inference",
        "autograd", "ops", "quantization", "sparsity", "text", "native",
        "distribution", "utils", "fft", "linalg"})


def Model(*args, **kwargs):
    from .hapi import Model as _M
    return _M(*args, **kwargs)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """reference: paddle.flops (hapi/dynamic_flops.py) — exact count via
    XLA cost analysis of the traced forward."""
    from .hapi.flops import flops as _flops
    return _flops(net, input_size, custom_ops, print_detail)


def summary(net, input_size=None, dtypes=None):
    """reference: paddle.summary — per-layer parameter table (shapes are
    not traced; the table reports parameter counts)."""
    from .hapi import Model as _M
    return _M(net).summary(input_size, dtypes)


def DataParallel(*args, **kwargs):
    from .distributed.parallel import DataParallel as _DP
    return _DP(*args, **kwargs)


from .autograd.engine import set_grad_enabled  # noqa: E402


def save(obj, path, **kwargs):
    from .framework.io import save as _save
    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load
    return _load(path, **kwargs)
