"""Encrypted model artifacts (AES-128-CTR).

Reference parity: paddle/fluid/framework/io/crypto/ (AES via cryptopp)
+ pybind/crypto.cc CipherFactory — encrypted save/load of inference
models and state dicts. The cipher core lives in native/ptnative.cc
(pt_aes128_ctr); a pure-Python AES serves as fallback AND as the
reference implementation the native kernel is tested against (the same
ref-vs-optimized pattern as the Pallas kernels).

Envelope format: b"PTENC2" || iv(16) || hmac_sha256(iv || body, 32) ||
body. The MAC (not a CRC — CTR is bit-malleable and the plaintext feeds
pickle, so integrity must be unforgeable) uses a key derived from the
user key separately from the encryption key.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os

_MAGIC = b"PTENC2"

_SBOX = None


def _sbox():
    global _SBOX
    if _SBOX is None:
        # generate the AES S-box from GF(2^8) inverses — avoids a 256-
        # entry literal and is self-checking against the native table
        p, q, box = 1, 1, [0] * 256
        box[0] = 0x63
        while True:
            # p := p * 3 in GF(2^8)
            p ^= ((p << 1) ^ (0x1B if p & 0x80 else 0)) & 0xFF
            # q := q / 3
            q ^= q << 1
            q ^= q << 2
            q ^= q << 4
            q &= 0xFF
            if q & 0x80:
                q ^= 0x09
            x = q ^ ((q << 1) | (q >> 7)) ^ ((q << 2) | (q >> 6)) ^ \
                ((q << 3) | (q >> 5)) ^ ((q << 4) | (q >> 4))
            box[p] = (x ^ 0x63) & 0xFF
            if p == 1:
                break
        _SBOX = box
    return _SBOX


def _xtime(x):
    return ((x << 1) ^ (0x1B if x & 0x80 else 0)) & 0xFF


def _expand_key(key16):
    sbox = _sbox()
    rcon = [0, 1, 2, 4, 8, 16, 32, 64, 128, 0x1B, 0x36]
    rk = list(key16)
    for i in range(4, 44):
        t = rk[4 * (i - 1):4 * i]
        if i % 4 == 0:
            t = [sbox[t[1]] ^ rcon[i // 4], sbox[t[2]], sbox[t[3]],
                 sbox[t[0]]]
        rk += [rk[4 * (i - 4) + j] ^ t[j] for j in range(4)]
    return rk


def _encrypt_block_py(rk, block):
    sbox = _sbox()
    s = [b ^ k for b, k in zip(block, rk[:16])]
    for rnd in range(1, 11):
        t = [0] * 16
        for c in range(4):
            for r in range(4):
                t[4 * c + r] = sbox[s[4 * ((c + r) & 3) + r]]
        if rnd < 10:
            s = []
            for c in range(4):
                a = t[4 * c:4 * c + 4]
                s += [
                    _xtime(a[0]) ^ (_xtime(a[1]) ^ a[1]) ^ a[2] ^ a[3],
                    a[0] ^ _xtime(a[1]) ^ (_xtime(a[2]) ^ a[2]) ^ a[3],
                    a[0] ^ a[1] ^ _xtime(a[2]) ^ (_xtime(a[3]) ^ a[3]),
                    (_xtime(a[0]) ^ a[0]) ^ a[1] ^ a[2] ^ _xtime(a[3]),
                ]
        else:
            s = t
        s = [v ^ k for v, k in zip(s, rk[16 * rnd:16 * rnd + 16])]
    return bytes(s)


def aes128_ctr_py(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    """Pure-Python AES-128-CTR (reference impl; slow — test/fallback)."""
    rk = _expand_key(key16)
    out = bytearray(len(data))
    ctr = bytearray(iv16)
    for off in range(0, len(data), 16):
        stream = _encrypt_block_py(rk, ctr)
        chunk = data[off:off + 16]
        out[off:off + len(chunk)] = bytes(
            a ^ b for a, b in zip(chunk, stream))
        for i in range(15, 7, -1):
            ctr[i] = (ctr[i] + 1) & 0xFF
            if ctr[i]:
                break
    return bytes(out)


def aes128_ctr(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    """CTR transform (encrypt == decrypt); native kernel when available."""
    import ctypes

    import numpy as np

    from .. import native
    lib = native.get_lib()
    if lib is None:
        return aes128_ctr_py(key16, iv16, data)
    src = np.frombuffer(data, dtype=np.uint8)
    dst = np.empty(len(data), dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.pt_aes128_ctr(
        (ctypes.c_uint8 * 16)(*key16), (ctypes.c_uint8 * 16)(*iv16),
        src.ctypes.data_as(u8p), dst.ctypes.data_as(u8p), len(data))
    if rc != 0:
        raise RuntimeError(f"pt_aes128_ctr rc={rc}")
    return dst.tobytes()


class AESCipher:
    """AES-128-CTR cipher with HMAC-SHA256 integrity, encrypt-then-MAC
    (the reference's AESCipher over cryptopp, io/crypto/aes_cipher.cc)."""

    def __init__(self, key: bytes):
        if not isinstance(key, (bytes, bytearray)):
            raise TypeError("key must be bytes")
        # derive independent encryption + MAC keys from the user key
        # (reference uses keyfiles; any key length accepted)
        self.key = hashlib.sha256(bytes(key) + b"|enc").digest()[:16]
        self._mac_key = hashlib.sha256(bytes(key) + b"|mac").digest()

    def _mac(self, iv: bytes, body: bytes) -> bytes:
        return _hmac.new(self._mac_key, iv + body,
                         hashlib.sha256).digest()

    def encrypt(self, plaintext: bytes) -> bytes:
        iv = os.urandom(16)
        body = aes128_ctr(self.key, iv, plaintext)
        return _MAGIC + iv + self._mac(iv, body) + body

    def decrypt(self, blob: bytes) -> bytes:
        if blob[:len(_MAGIC)] != _MAGIC:
            if blob[:6] == b"PTENC1":
                raise ValueError(
                    "legacy PTENC1 artifact (pre-release CRC envelope); "
                    "re-save it with this version")
            raise ValueError("not a PTENC2 encrypted blob")
        off = len(_MAGIC)
        iv = blob[off:off + 16]
        tag = blob[off + 16:off + 48]
        body = blob[off + 48:]
        if not _hmac.compare_digest(tag, self._mac(iv, body)):
            raise ValueError("decryption integrity check failed "
                             "(wrong key or corrupted file)")
        return aes128_ctr(self.key, iv, body)

    def encrypt_to_file(self, plaintext: bytes, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read())


class CipherFactory:
    """Reference API shape: CipherFactory.create_cipher() -> cipher."""

    @staticmethod
    def create_cipher(key: bytes = b"") -> AESCipher:
        if not key:
            key = CipherFactory.generate_key()
        return AESCipher(key)

    @staticmethod
    def generate_key() -> bytes:
        return os.urandom(16)
