"""Framework utilities: save/load, seeding."""

from .io import load, save
