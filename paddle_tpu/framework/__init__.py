"""Framework utilities: save/load, seeding, trainer runtime."""

from .io import load, save
from .trainer import (DeviceWorker, DistMultiTrainer, DownpourWorker,
                      HogwildWorker, MultiTrainer, TrainerFactory)
