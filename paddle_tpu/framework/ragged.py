"""RaggedTensor: the TPU-native stand-in for the reference's LoDTensor.

The reference carries ragged "level of detail" offsets on the tensor
itself (paddle/fluid/framework/lod_tensor.h:109) and runs variable-length
kernels over them. Under XLA, shapes must be static, so the design here is
split in two:

- **Host-side container** (this class): ``values`` + ``row_splits`` exactly
  like a 1-level LoD, used in the data pipeline (datasets, feeds, PS slot
  parsing). Conversion to/from the device representation is explicit.
- **Device representation**: a dense padded array ``[batch, maxlen, ...]``
  plus an int32 ``lengths [batch]`` vector. All sequence ops
  (paddle_tpu.ops.sequence) consume this pair — masks instead of offsets,
  so everything jits and tiles onto the MXU.
"""

from __future__ import annotations

import numpy as np

__all__ = ["RaggedTensor"]


class RaggedTensor:
    """1-level ragged batch: ``values`` flattened along dim 0, row i owning
    ``values[row_splits[i]:row_splits[i+1]]``."""

    def __init__(self, values, row_splits):
        self.values = np.asarray(values)
        self.row_splits = np.asarray(row_splits, dtype=np.int64)
        if self.row_splits.ndim != 1 or self.row_splits[0] != 0:
            raise ValueError("row_splits must be 1-D starting at 0")
        if int(self.row_splits[-1]) != self.values.shape[0]:
            raise ValueError(
                f"row_splits end {int(self.row_splits[-1])} != "
                f"values rows {self.values.shape[0]}")

    # -- constructors -------------------------------------------------
    @staticmethod
    def from_rows(rows):
        rows = [np.asarray(r) for r in rows]
        splits = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum([r.shape[0] for r in rows], out=splits[1:])
        values = (np.concatenate(rows, axis=0) if rows
                  else np.zeros((0,), dtype=np.float32))
        return RaggedTensor(values, splits)

    @staticmethod
    def from_padded(padded, lengths):
        padded = np.asarray(padded)
        lengths = np.asarray(lengths, dtype=np.int64)
        return RaggedTensor.from_rows(
            [padded[i, : int(n)] for i, n in enumerate(lengths)])

    # -- views --------------------------------------------------------
    @property
    def lengths(self):
        return np.diff(self.row_splits)

    @property
    def nrows(self):
        return len(self.row_splits) - 1

    def row(self, i):
        return self.values[self.row_splits[i]:self.row_splits[i + 1]]

    def rows(self):
        return [self.row(i) for i in range(self.nrows)]

    def __len__(self):
        return self.nrows

    def __repr__(self):
        return (f"RaggedTensor(nrows={self.nrows}, "
                f"values={self.values.shape}, dtype={self.values.dtype})")

    # -- device bridge ------------------------------------------------
    def to_padded(self, maxlen=None, pad_value=0):
        """Return ``(padded [nrows, maxlen, ...], lengths [nrows])`` — the
        static-shape device representation."""
        lengths = self.lengths
        m = int(maxlen) if maxlen is not None else int(lengths.max(initial=0))
        tail = self.values.shape[1:]
        out = np.full((self.nrows, m) + tail, pad_value,
                      dtype=self.values.dtype)
        for i in range(self.nrows):
            n = min(int(lengths[i]), m)
            out[i, :n] = self.row(i)[:n]
        return out, np.minimum(lengths, m).astype(np.int32)

    def concat(self, other):
        return RaggedTensor.from_rows(self.rows() + other.rows())
