"""Checkpoint save/load.

Reference parity: python/paddle/framework/io.py (save:565 / load:781 —
pickled nested state_dicts of params + optimizer state). Arrays are stored
as numpy inside the pickle; an orbax-backed sharded async checkpoint path
for large distributed models lives in paddle_tpu.distributed.checkpoint.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import numpy as np

from ..tensor import Parameter, Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__pt_tensor__": True, "data": np.asarray(obj.value),
                "name": obj.name,
                "is_parameter": isinstance(obj, Parameter),
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, jax.Array):
        return {"__pt_tensor__": True, "data": np.asarray(obj),
                "name": None, "is_parameter": False, "stop_gradient": True}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__pt_tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_parameter") else Tensor
            if cls is Parameter:
                t = Parameter(jax.numpy.asarray(obj["data"]),
                              name=obj.get("name"))
            else:
                t = Tensor(jax.numpy.asarray(obj["data"]),
                           stop_gradient=obj.get("stop_gradient", True),
                           name=obj.get("name"))
            return t
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4,
         cipher_key: bytes = None) -> None:
    """paddle.save equivalent: pickle state_dict-like nests. With
    ``cipher_key``, the artifact is AES-128-CTR encrypted (reference:
    encrypted model save via io/crypto CipherFactory)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if cipher_key is None:  # stream — no full-blob copy in host RAM
        with open(path, "wb") as f:
            pickle.dump(_to_saveable(obj), f, protocol=protocol)
        return
    from .crypto import AESCipher
    blob = AESCipher(cipher_key).encrypt(
        pickle.dumps(_to_saveable(obj), protocol=protocol))
    with open(path, "wb") as f:
        f.write(blob)


def load(path: str, return_numpy: bool = False, cipher_key: bytes = None,
         **kwargs) -> Any:
    from .crypto import _MAGIC
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if cipher_key is not None:
            from .crypto import AESCipher
            raw = pickle.loads(
                AESCipher(cipher_key).decrypt(head + f.read()))
        elif head == _MAGIC:
            raise ValueError(
                f"{path!r} is an encrypted artifact; pass cipher_key=")
        else:  # stream — no full-blob copy in host RAM
            f.seek(0)
            raw = pickle.load(f)
    return _from_saveable(raw, return_numpy)
