"""Unique-name generator (reference: python/paddle/fluid/unique_name.py —
generate/switch/guard over a per-scope counter stack)."""

from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "switch", "guard"]


class _NameGenerator:
    def __init__(self):
        self.ids: dict[str, int] = defaultdict(int)

    def __call__(self, key: str) -> str:
        n = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{n}"


_generator = _NameGenerator()


def generate(key: str) -> str:
    """Return `key_N` with a process-wide increasing N per key."""
    return _generator(key)


def switch(new_generator: _NameGenerator | None = None) -> _NameGenerator:
    """Swap the active generator, returning the previous one."""
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None \
        else _NameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator: _NameGenerator | None = None):
    """Scope with a fresh (or given) name generator."""
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
