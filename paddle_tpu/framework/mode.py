"""Execution-mode switch + small framework-level utilities.

Reference parity: paddle.enable_static/disable_static/in_dynamic_mode
(python/paddle/fluid/framework.py _dygraph_tracer switch), paddle.batch
(python/paddle/batch.py), check_shape (fluid/layers/utils.py:364),
set_printoptions (tensor/to_string.py).

TPU-native stance: there is no op-by-op static interpreter — "static mode"
means building Programs by tracing (paddle_tpu.static.build_program /
program_guard). The mode flag exists so reference code that branches on
``in_dynamic_mode()`` behaves, and ``enable_static`` makes
``paddle.static.default_main_program`` the capture target.
"""

from __future__ import annotations

import numpy as np

_dynamic_mode = True


def enable_static() -> None:
    global _dynamic_mode
    _dynamic_mode = False


def disable_static() -> None:
    global _dynamic_mode
    _dynamic_mode = True


def in_dynamic_mode() -> bool:
    return _dynamic_mode


# Alias used throughout fluid-era reference code.
def in_dygraph_mode() -> bool:
    return _dynamic_mode


def batch(reader, batch_size, drop_last: bool = False):
    """Wrap a sample reader into a mini-batch reader
    (reference: paddle.batch, python/paddle/batch.py:18)."""
    if batch_size <= 0:
        raise ValueError("batch_size must be positive")

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def check_shape(shape) -> None:
    """Validate a shape argument (reference: fluid/layers/utils.py:364)."""
    from ..tensor import Tensor
    if isinstance(shape, Tensor):
        if shape.dtype not in (np.int32, np.int64):
            raise TypeError(
                f"shape tensor must be int32/int64, got {shape.dtype}")
        return
    if not isinstance(shape, (list, tuple)):
        raise TypeError(f"shape must be a list/tuple/Tensor, got "
                        f"{type(shape).__name__}")
    for s in shape:
        if not isinstance(s, (int, np.integer)) and not hasattr(s, "dtype"):
            raise TypeError(f"shape elements must be ints, got "
                            f"{type(s).__name__}")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None) -> None:
    """Tensor print formatting (reference: paddle.set_printoptions,
    tensor/to_string.py). Tensor repr renders via numpy, so this delegates
    to numpy's print options."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)
