"""Trainer / device-worker runtime over heavy-IO datasets.

TPU-native equivalent of the reference's trainer fleet runtime
(reference: paddle/fluid/framework/trainer.h:102 MultiTrainer, :137
DistMultiTrainer; device_worker.h:244 HogwildWorker, :275 DownpourWorker;
driven from Python by fluid/trainer_factory.py + executor.py:1662
train_from_dataset). The reference runs N C++ device-worker threads, each
interpreting the program over its DataFeed channel; here each worker drives
ONE jitted step function over its channel, so the hot loop is a single XLA
launch per batch and workers overlap host-side batch prep with device
execution. Hogwild semantics (lock-free shared state) map to workers
applying updates to a shared functional state slot without coordination;
Downpour semantics map to push-grad / pull-param against the PS client
between steps.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class DeviceWorker:
    """Base: one worker thread bound to a dataset channel."""

    def __init__(self):
        self.metrics: Dict[str, float] = {"steps": 0, "loss_sum": 0.0}

    def bind(self, trainer, worker_id: int, channel) -> None:
        self.trainer = trainer
        self.worker_id = worker_id
        self.channel = channel

    def train_loop(self) -> None:
        raise NotImplementedError

    @property
    def avg_loss(self) -> float:
        n = max(1, int(self.metrics["steps"]))
        return self.metrics["loss_sum"] / n


class HogwildWorker(DeviceWorker):
    """Lock-free shared-state worker (reference device_worker.h:244).

    Each worker pulls batches from its channel and calls the trainer's
    step function against the SHARED state (reads and writes race by
    design — hogwild). With a jitted TrainStep the 'state' is the step
    object's params/opt_state, mutated without a lock."""

    def train_loop(self) -> None:
        for batch in self.channel:
            loss = self.trainer._run_step(batch, self.worker_id)
            self.metrics["steps"] += 1
            if loss is not None and np.ndim(loss) == 0:
                self.metrics["loss_sum"] += float(loss)


class DownpourWorker(DeviceWorker):
    """Async-PS worker (reference device_worker.h:275 DownpourWorker):
    pull dense params from the PS, run the local step, push gradients —
    no barrier between workers or trainers."""

    def train_loop(self) -> None:
        trainer = self.trainer
        for batch in self.channel:
            # The pull->step->push cycle is atomic per worker: the jitted
            # step donates the state buffers the pull installed, so a
            # concurrent worker's push must not read them mid-donation.
            # Asynchrony between TRAINERS (processes) is preserved — the
            # reference's async-PS property — only threads of one trainer
            # serialize, as they already do at the single device.
            with trainer._lock:
                trainer._pull_dense(self.worker_id)
                trainer._pull_sparse(batch)
                loss = trainer._run_step(batch, self.worker_id)
                trainer._push_dense(self.worker_id)
                trainer._push_sparse(batch)
            self.metrics["steps"] += 1
            if loss is not None and np.ndim(loss) == 0:
                self.metrics["loss_sum"] += float(loss)


class MultiTrainer:
    """Runs N device workers over a Dataset's channels
    (reference trainer.h:102 MultiTrainer::Run).

    step_fn(batch, worker_id) -> loss is typically a jitted TrainStep
    bound to shared state; thread-level overlap hides host batch prep
    behind device steps (the reference's reason for multi-threading the
    op interpreter does not apply to one fused XLA launch, but IO overlap
    still does)."""

    worker_cls = HogwildWorker

    def __init__(self, step_fn: Callable[[Any, int], Any],
                 thread_num: int = 2):
        self.step_fn = step_fn
        self.thread_num = max(1, int(thread_num))
        self.workers: List[DeviceWorker] = []
        self._lock = threading.RLock()

    # hooks for DistMultiTrainer
    def _pull_dense(self, worker_id: int) -> None:  # pragma: no cover
        pass

    def _push_dense(self, worker_id: int) -> None:  # pragma: no cover
        pass

    def _pull_sparse(self, batch) -> None:  # pragma: no cover
        pass

    def _push_sparse(self, batch) -> None:  # pragma: no cover
        pass

    def _run_step(self, batch, worker_id: int):
        # One device executes one program at a time, and jitted steps
        # donate their state buffers — so the DEVICE step serializes
        # under the trainer lock while workers overlap host-side batch
        # prep/IO. (The reference's per-parameter hogwild races are a
        # CPU-interpreter property with no TPU analog.)
        with self._lock:
            return self.step_fn(batch, worker_id)

    def run(self, dataset, debug: bool = False) -> Dict[str, float]:
        channels = self._channels(dataset)
        self.workers = []
        threads = []
        for i, ch in enumerate(channels):
            w = self.worker_cls()
            w.bind(self, i, ch)
            self.workers.append(w)
        errors: List[BaseException] = []

        def guarded(w):
            try:
                w.train_loop()
            except BaseException as e:  # propagate to the caller
                errors.append(e)

        for w in self.workers:
            t = threading.Thread(target=guarded, args=(w,), daemon=True)
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        steps = sum(int(w.metrics["steps"]) for w in self.workers)
        loss_sum = sum(w.metrics["loss_sum"] for w in self.workers)
        return {"steps": steps,
                "avg_loss": loss_sum / max(1, steps)}

    def _channels(self, dataset) -> List[Any]:
        bs = getattr(dataset, "batch_size", 1)
        drop_last = getattr(dataset, "drop_last", False)

        def batched(samples):
            out, cur = [], []
            for s in samples:
                cur.append(s)
                if len(cur) == bs:
                    out.append(cur)
                    cur = []
            if cur and not drop_last:
                out.append(cur)
            return out

        if hasattr(dataset, "channels"):  # InMemoryDataset
            return [batched(c)
                    for c in dataset.channels(self.thread_num)]
        # QueueDataset / any iterable of batches: STREAM from one shared
        # iterator (the dataset's own bounded queue provides the
        # backpressure) — draining it up front would defeat the queue and
        # buffer the whole epoch in host memory.
        src = iter(dataset)
        src_lock = threading.Lock()

        def shared_stream():
            while True:
                with src_lock:
                    try:
                        b = next(src)
                    except StopIteration:
                        return
                yield b

        return [shared_stream() for _ in range(self.thread_num)]


class DistMultiTrainer(MultiTrainer):
    """PS-mode trainer (reference trainer.h:137): Downpour workers sync
    dense tables with the PS client around each local step."""

    worker_cls = DownpourWorker

    def __init__(self, step_fn, thread_num: int = 2, ps_client=None,
                 dense_table: str = "dense_0",
                 get_dense: Optional[Callable[[], np.ndarray]] = None,
                 set_dense: Optional[Callable[[np.ndarray], None]] = None,
                 get_grad: Optional[Callable[[], np.ndarray]] = None,
                 sparse_pull: Optional[Callable] = None,
                 sparse_push: Optional[Callable] = None):
        super().__init__(step_fn, thread_num)
        self.ps_client = ps_client
        self.dense_table = dense_table
        self._get_dense = get_dense
        self._set_dense = set_dense
        self._get_grad = get_grad
        # sparse hooks (reference DownpourWorker sparse tables / the
        # heter-PS split: embedding rows live server-side; each cycle
        # pulls the batch's rows and pushes their grads):
        # sparse_pull(ps_client, batch), sparse_push(ps_client, batch)
        self._sparse_pull = sparse_pull
        self._sparse_push = sparse_push

    def _pull_dense(self, worker_id: int) -> None:
        if self.ps_client is None or self._set_dense is None:
            return
        self._set_dense(self.ps_client.pull_dense(self.dense_table))

    def _push_dense(self, worker_id: int) -> None:
        if self.ps_client is None or self._get_grad is None:
            return
        g = self._get_grad()
        if g is not None:
            self.ps_client.push_dense_grad(self.dense_table, g)

    def _pull_sparse(self, batch) -> None:
        if self.ps_client is not None and self._sparse_pull is not None:
            self._sparse_pull(self.ps_client, batch)

    def _push_sparse(self, batch) -> None:
        if self.ps_client is not None and self._sparse_push is not None:
            self._sparse_push(self.ps_client, batch)


class TrainerFactory:
    """reference fluid/trainer_factory.py — picks the trainer class from a
    mode string."""

    _TRAINERS = {"MultiTrainer": MultiTrainer,
                 "DistMultiTrainer": DistMultiTrainer}

    @classmethod
    def create(cls, name: str, *args, **kwargs):
        if name not in cls._TRAINERS:
            from ..core.enforce import NotFoundError
            raise NotFoundError(f"unknown trainer {name!r}; have "
                                f"{sorted(cls._TRAINERS)}")
        return cls._TRAINERS[name](*args, **kwargs)

