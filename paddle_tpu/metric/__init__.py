"""Streaming metrics (reference: python/paddle/metric/metrics.py —
Metric base, Accuracy, Precision, Recall, Auc)."""

from __future__ import annotations

import numpy as np

from ..tensor import Tensor


def _np(x):
    return np.asarray(x.value if isinstance(x, Tensor) else x)


class Metric:
    def __init__(self):
        self._name = type(self).__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Optional pre-processing on device; default passthrough."""
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.argmax(-1)
        correct = (idx == label_np[..., None])
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0] if correct.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].sum()
            self.count[i] += num
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else list(accs)

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).ravel()
        l = _np(labels).astype(np.int32).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).ravel()
        l = _np(labels).astype(np.int32).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).ravel()
        if preds.ndim == 2 and preds.shape[1] == 2:
            scores = preds[:, 1]
        else:
            scores = preds.ravel()
        bins = np.clip((scores * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds descending
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2.0
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    """Functional accuracy (reference: paddle.metric.accuracy). The
    optional correct/total output tensors are filled in place when
    given (reference accuracy_op outputs)."""
    import jax.numpy as jnp
    from .. import dispatch
    topk_vals, topk_idx = dispatch.wrapped_ops["topk"](input, k)
    lbl = label.value if isinstance(label, Tensor) else label
    idx = topk_idx.value if isinstance(topk_idx, Tensor) else topk_idx
    if lbl.ndim == 1:
        lbl = lbl[:, None]
    hit = (idx == lbl).any(axis=-1)
    n_correct = hit.astype(jnp.int64).sum()
    if correct is not None and hasattr(correct, "_inplace_assign"):
        correct._inplace_assign(Tensor(n_correct))
    if total is not None and hasattr(total, "_inplace_assign"):
        total._inplace_assign(Tensor(jnp.asarray(hit.size)))
    return Tensor(jnp.mean(hit.astype(jnp.float32)))
