"""Utility subpackage (reference: python/paddle/utils/)."""

from . import cpp_extension, download
from ..framework import unique_name
from .download import get_path_from_url, get_weights_path_from_url
from .install_check import run_check


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference:
    paddle.utils.deprecated, utils/deprecated.py): warns on call."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        wrapper.__deprecated__ = True
        return wrapper
    return deco


def require_version(min_version: str, max_version=None) -> None:
    """Check the installed framework version against bounds (reference:
    paddle.utils.require_version)."""
    import paddle_tpu

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(paddle_tpu.__version__)
    if parse(min_version) > cur:
        raise RuntimeError(
            f"paddle_tpu>={min_version} required, found "
            f"{paddle_tpu.__version__}")
    if max_version is not None and parse(max_version) < cur:
        raise RuntimeError(
            f"paddle_tpu<={max_version} required, found "
            f"{paddle_tpu.__version__}")


def try_import(module_name: str, err_msg: str = None):
    """Import a soft dependency with a friendly error (reference:
    paddle.utils.lazy_import.try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Optional dependency {module_name!r} is not "
            f"installed; install it to use this feature") from None
