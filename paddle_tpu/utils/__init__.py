"""Utility subpackage (reference: python/paddle/utils/)."""

from . import cpp_extension
