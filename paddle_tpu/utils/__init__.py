"""Utility subpackage (reference: python/paddle/utils/)."""

from . import cpp_extension, download
from ..framework import unique_name
from .download import get_path_from_url, get_weights_path_from_url
from .install_check import run_check
