"""Weight/dataset download cache (reference:
python/paddle/utils/download.py get_weights_path_from_url:75,
get_path_from_url:121).

Same cache layout (~/.cache/paddle_tpu/weights/<name>) and md5 check; the
network fetch uses urllib with retries. In air-gapped environments, a file
already present in the cache (or a file:// URL) is used without any
network access.
"""

from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil
import tarfile
import time
import zipfile

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle_tpu/weights")
DOWNLOAD_RETRY_LIMIT = 3

__all__ = ["get_weights_path_from_url", "get_path_from_url", "is_url"]


def is_url(path: str) -> bool:
    return path.startswith(("http://", "https://", "file://"))


def _md5check(fullname: str, md5sum: str | None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _download(url: str, path: str) -> str:
    os.makedirs(path, exist_ok=True)
    fname = osp.split(url)[-1]
    fullname = osp.join(path, fname)
    if url.startswith("file://"):
        shutil.copy(url[len("file://"):], fullname)
        return fullname
    import urllib.request
    last_err = None
    for attempt in range(DOWNLOAD_RETRY_LIMIT):
        try:
            tmp = fullname + ".tmp"
            urllib.request.urlretrieve(url, tmp)
            os.replace(tmp, fullname)
            return fullname
        except Exception as e:  # noqa: BLE001 - retry any fetch error
            last_err = e
            time.sleep(1 + attempt)
    raise RuntimeError(f"download of {url} failed after "
                       f"{DOWNLOAD_RETRY_LIMIT} tries: {last_err}")


def _decompress(fname: str) -> str:
    """Extract beside the archive. Returns the single top-level directory
    when the archive has one (the usual weights layout), else the
    directory holding the extracted members."""
    dirpath = osp.dirname(fname)
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as tf:
            names = tf.getnames()
            tf.extractall(dirpath, filter="data")
    elif zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as zf:
            names = zf.namelist()
            zf.extractall(dirpath)
    else:
        return fname
    roots = {n.split("/")[0] for n in names if n}
    if len(roots) == 1:
        top = osp.join(dirpath, next(iter(roots)))
        if osp.isdir(top):
            return top
    return dirpath


def get_path_from_url(url: str, root_dir: str | None = None,
                      md5sum: str | None = None,
                      check_exist: bool = True,
                      decompress: bool = True) -> str:
    """Fetch (or reuse cached) `url` under `root_dir`; optionally unpack."""
    root_dir = root_dir or WEIGHTS_HOME
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    marker = fullname + ".extracted"
    if check_exist and osp.exists(fullname) and _md5check(fullname, md5sum):
        cached = True  # no network
    else:
        fullname = _download(url, root_dir)
        if not _md5check(fullname, md5sum):
            raise RuntimeError(f"md5 mismatch for {url}")
        cached = False
    if decompress and (tarfile.is_tarfile(fullname)
                       or zipfile.is_zipfile(fullname)):
        # skip re-extraction on cache hits (marker records the result path)
        if cached and osp.exists(marker):
            return open(marker).read().strip()
        out = _decompress(fullname)
        with open(marker, "w") as f:
            f.write(out)
        return out
    return fullname


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    """Download weights to the shared cache, return the local path."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum, decompress=False)
