"""Installation self-check (reference:
python/paddle/utils/install_check.py run_check:162).

Runs a tiny linear-regression fit twice — eagerly and under jit — on the
current default device, and (when more than one device is visible) once
more data-parallel over all of them, then prints the verdict the way the
reference's `paddle.utils.run_check()` does.
"""

from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def _simple_network():
    import paddle_tpu as pt
    from paddle_tpu import nn

    model = nn.Linear(4, 1)
    x = pt.to_tensor(np.random.default_rng(0)
                     .standard_normal((16, 4)).astype(np.float32))
    y = pt.to_tensor(np.ones((16, 1), np.float32))
    return model, x, y


def _run_single() -> None:
    import paddle_tpu.optimizer as optim

    model, x, y = _simple_network()
    opt = optim.SGD(learning_rate=0.1, parameters=model.parameters())
    for _ in range(3):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert np.isfinite(float(loss)), "single-device training diverged"


def _run_jit() -> None:
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStep

    model, x, y = _simple_network()
    opt = optim.SGD(learning_rate=0.1)
    step = TrainStep(model, opt, lambda m, b: ((m(b[0]) - b[1]) ** 2).mean())
    l0 = float(step((x.value, y.value)))
    l1 = float(step((x.value, y.value)))
    assert np.isfinite(l0) and l1 < l0, "jitted training did not descend"


def _run_parallel(n: int) -> None:
    import paddle_tpu as pt
    import paddle_tpu.optimizer as optim
    from paddle_tpu.distributed import DistributedStrategy, fleet

    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": n, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
    fleet.init(strategy=strategy)
    model, x, y = _simple_network()
    opt = fleet.distributed_optimizer(
        optim.SGD(learning_rate=0.1), strategy)
    step = fleet.distributed_jit(
        model, opt, lambda m, b: ((m(pt.Tensor(b[0])) - b[1]) ** 2).mean())
    loss = step((np.tile(np.asarray(x.value), (n, 1)),
                 np.tile(np.asarray(y.value), (n, 1))))
    assert np.isfinite(float(loss)), "data-parallel step diverged"


def run_check() -> None:
    """Verify the install: eager, jitted, and (if possible) multi-device."""
    import jax

    devs = jax.devices()
    plat = devs[0].platform
    print(f"Running verify on {len(devs)} {plat} device(s).")
    _run_single()
    _run_jit()
    if len(devs) > 1:
        try:
            _run_parallel(len(devs))
            print(f"paddle_tpu works on {len(devs)} devices.")
        except Exception as e:  # noqa: BLE001 - report, single still valid
            print(f"multi-device check failed ({e}); "
                  "single-device install is healthy.")
    print("paddle_tpu is installed successfully!")
