"""Build + load out-of-tree custom C/C++ ops (PD_BUILD_OP analog).

Reference parity: python/paddle/utils/cpp_extension (JIT-compiles user
C++/CUDA ops with setuptools and registers them) and
paddle/fluid/extension/ext_op_meta_info.h:502 (PD_BUILD_OP ABI). The
TPU-native adaptation: custom kernels are HOST ops — they execute inside
``jax.pure_callback`` so they compose with jit/pjit (XLA stages a host
callback around the C call), and an optional ``ptop_<name>_backward``
symbol is wired through ``jax.custom_vjp`` the same way the reference
synthesizes a grad op from the user's grad kernel.

Usage::

    op = load(name="relu2", sources=["my_op.cc"])   # g++ -shared
    y = op(x)                       # eager or inside jit
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_MAX_RANK = 8

_DTYPE_TO_CODE = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4, np.dtype(np.bool_): 5,
}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}

_INCLUDE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")


class _PTOpTensor(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("dims", ctypes.c_int64 * _MAX_RANK),
                ("ndim", ctypes.c_int32),
                ("dtype", ctypes.c_int32)]


def _as_struct(arr: np.ndarray) -> _PTOpTensor:
    t = _PTOpTensor()
    t.data = arr.ctypes.data_as(ctypes.c_void_p)
    for i, d in enumerate(arr.shape):
        t.dims[i] = d
    t.ndim = arr.ndim
    t.dtype = _DTYPE_TO_CODE[arr.dtype]
    return t


def build_extension(sources: Sequence[str], name: str = "ptop_ext",
                    extra_cflags: Sequence[str] = (),
                    build_dir: Optional[str] = None) -> str:
    """Compile sources into a shared library; returns its path
    (the reference's setuptools JIT build, reduced to one g++ call —
    no CUDA arch plumbing needed on this stack)."""
    build_dir = build_dir or tempfile.mkdtemp(prefix=f"{name}_build_")
    out = os.path.join(build_dir, f"lib{name}.so")
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{_INCLUDE_DIR}", *extra_cflags, *sources, "-o", out]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"custom-op build failed:\n{' '.join(cmd)}\n{proc.stderr}")
    return out


class CustomOp:
    """A loaded custom op: callable on jax arrays, jit-compatible."""

    def __init__(self, name: str, lib_path: str, n_outputs: int = 1,
                 shape_fn: Optional[Callable] = None):
        self.name = name
        self.lib = ctypes.CDLL(lib_path)
        self.n_outputs = n_outputs
        self._fwd = getattr(self.lib, f"ptop_{name}_forward")
        self._fwd.restype = ctypes.c_int
        self._bwd = getattr(self.lib, f"ptop_{name}_backward", None)
        if self._bwd is not None:
            self._bwd.restype = ctypes.c_int
        self._infer = getattr(self.lib, f"ptop_{name}_infer", None)
        if self._infer is not None:
            self._infer.restype = ctypes.c_int
        if self._infer is None and shape_fn is None:
            raise ValueError(
                f"op {name!r} exports no ptop_{name}_infer; pass shape_fn")
        self.shape_fn = shape_fn
        self._call = self._build_call()

    # ---------------------------------------------------------- shapes
    def _out_specs(self, avals):
        """[(shape, dtype)] for outputs, via C infer fn or shape_fn."""
        if self.shape_fn is not None:
            specs = self.shape_fn(*[(tuple(a.shape), a.dtype)
                                    for a in avals])
            return [(tuple(s), np.dtype(d)) for s, d in specs]
        n_in = len(avals)
        in_dims = (ctypes.c_int64 * (n_in * _MAX_RANK))()
        in_ndims = (ctypes.c_int32 * n_in)()
        in_dtypes = (ctypes.c_int32 * n_in)()
        for i, a in enumerate(avals):
            for j, d in enumerate(a.shape):
                in_dims[i * _MAX_RANK + j] = d
            in_ndims[i] = len(a.shape)
            in_dtypes[i] = _DTYPE_TO_CODE[np.dtype(a.dtype)]
        out_dims = (ctypes.c_int64 * (self.n_outputs * _MAX_RANK))()
        out_ndims = (ctypes.c_int32 * self.n_outputs)()
        out_dtypes = (ctypes.c_int32 * self.n_outputs)()
        rc = self._infer(in_dims, in_ndims, in_dtypes, n_in,
                         out_dims, out_ndims, out_dtypes, self.n_outputs)
        if rc != 0:
            raise RuntimeError(f"op {self.name!r} infer failed rc={rc}")
        return [
            (tuple(out_dims[i * _MAX_RANK + j]
                   for j in range(out_ndims[i])),
             _CODE_TO_DTYPE[out_dtypes[i]])
            for i in range(self.n_outputs)]

    # ------------------------------------------------------------ exec
    def _run_c(self, fn, inputs, out_specs):
        ins = [np.ascontiguousarray(np.asarray(x)) for x in inputs]
        outs = [np.zeros(s, dtype=d) for s, d in out_specs]
        in_arr = (_PTOpTensor * len(ins))(*[_as_struct(a) for a in ins])
        out_arr = (_PTOpTensor * len(outs))(*[_as_struct(a) for a in outs])
        rc = fn(in_arr, len(ins), out_arr, len(outs))
        if rc != 0:
            raise RuntimeError(f"op {self.name!r} kernel rc={rc}")
        return outs

    def _build_call(self):
        def raw(*xs):
            specs = self._out_specs([jax.ShapeDtypeStruct(np.shape(x),
                                                          x.dtype)
                                     for x in xs])
            shape_dtypes = [jax.ShapeDtypeStruct(s, d) for s, d in specs]
            out = jax.pure_callback(
                lambda *h: tuple(self._run_c(self._fwd, h, specs)),
                tuple(shape_dtypes), *xs)
            return out if self.n_outputs > 1 else out[0]

        if self._bwd is None:
            return raw

        bwd_c = self._bwd

        @jax.custom_vjp
        def op(*xs):
            return raw(*xs)

        def fwd_rule(*xs):
            y = raw(*xs)
            return y, (xs, y)

        def bwd_rule(res, g):
            xs, y = res
            ys = y if isinstance(y, tuple) else (y,)
            gs = g if isinstance(g, tuple) else (g,)
            gspecs = [(tuple(np.shape(x)), np.dtype(x.dtype)) for x in xs]
            gshapes = [jax.ShapeDtypeStruct(s, d) for s, d in gspecs]
            grads = jax.pure_callback(
                lambda *h: tuple(self._run_c(bwd_c, h, gspecs)),
                tuple(gshapes), *xs, *ys, *gs)
            return tuple(grads)

        op.defvjp(fwd_rule, bwd_rule)
        return op

    def __call__(self, *xs):
        from ..tensor import Tensor
        wrap = any(isinstance(x, Tensor) for x in xs)
        xs = [x.value if isinstance(x, Tensor) else jnp.asarray(x)
              for x in xs]
        out = self._call(*xs)
        if wrap:
            out = (tuple(Tensor(o) for o in out)
                   if isinstance(out, tuple) else Tensor(out))
        return out


def load(name: str, sources: Sequence[str] = (),
         lib_path: Optional[str] = None, n_outputs: int = 1,
         shape_fn: Optional[Callable] = None,
         extra_cflags: Sequence[str] = (),
         build_dir: Optional[str] = None,
         register: bool = True) -> CustomOp:
    """Compile (if sources given) and load custom op ``name``; registers
    it in the op registry so it's visible framework-wide (the reference
    returns a module of generated python wrappers)."""
    if lib_path is None:
        if not sources:
            raise ValueError("need sources or lib_path")
        lib_path = build_extension(sources, name=name,
                                   extra_cflags=extra_cflags,
                                   build_dir=build_dir)
    op = CustomOp(name, lib_path, n_outputs=n_outputs, shape_fn=shape_fn)
    if register:
        from ..ops.registry import register_op
        # overwrite on re-load so a recompiled kernel wins
        register_op(name, op, module="custom",
                    differentiable=op._bwd is not None)
    return op
