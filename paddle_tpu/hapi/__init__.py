"""High-level API (reference parity: python/paddle/hapi/)."""

from . import callbacks
from .callbacks import (Callback, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ProgBarLogger,
                        ReduceLROnPlateau, VisualDL)
from .flops import flops
from .model import Model
