"""High-level API (reference parity: python/paddle/hapi/)."""

from . import callbacks
from .callbacks import (Callback, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ProgBarLogger, ReduceLROnPlateau)
from .flops import flops
from .model import Model
