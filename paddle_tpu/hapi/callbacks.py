"""Training callbacks (reference: python/paddle/hapi/callbacks.py —
Callback base, ProgBarLogger, ModelCheckpoint:532, LRScheduler,
EarlyStopping:687, ReduceLROnPlateau, VisualDL)."""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatcher(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return dispatcher
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in
                              (logs or {}).items())
            print(f"step {step}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = ", ".join(f"{k}: {_fmt(v)}" for k, v in
                              (logs or {}).items())
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {items}")


def _fmt(v):
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    """reference: hapi/callbacks.py:532 — save every epoch."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir or "checkpoints"

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """reference: hapi/callbacks.py:687."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0
        self.wait = 0
        self.best = None
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).ravel()[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.model is not None:
                    self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: best {self.monitor}="
                          f"{self.best}")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.min_delta = min_delta
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None or self.model is None:
            return
        cur = float(np.asarray(cur).ravel()[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        better = self.best is None or (
            cur < self.best - self.min_delta if self.mode == "min"
            else cur > self.best + self.min_delta)
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                try:
                    lr = opt.get_lr()
                    opt.set_lr(max(lr * self.factor, self.min_lr))
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr -> {opt.get_lr()}")
                except Exception:
                    pass
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logging to a logdir (reference: hapi/callbacks.py:839
    VisualDL callback over the visualdl LogWriter). The visualdl package
    is CUDA-ecosystem tooling; here scalars stream to
    ``<log_dir>/scalars-<mode>.jsonl`` (one {"tag", "step", "value"}
    record per line — trivially loadable into pandas/TensorBoard), and
    ``read_scalars`` loads them back."""

    def __init__(self, log_dir: str = "./log"):
        super().__init__()
        self.log_dir = log_dir
        self.epochs = None
        self._writers = {}
        self._train_step = 0

    def _writer(self, mode: str):
        import os
        w = self._writers.get(mode)
        if w is None:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(self.log_dir, f"scalars-{mode}.jsonl")
            # one file per callback instance (a fresh fit() run starts a
            # fresh log; appending would interleave restarting steps)
            w = open(path, "w")
            self._writers[mode] = w
        return w

    def _log(self, mode: str, step: int, logs) -> None:
        import json as _json
        w = self._writer(mode)
        for tag, value in (logs or {}).items():
            try:
                vals = np.asarray(value).ravel()
                if not len(vals):
                    continue
                v = float(vals[0])
            except (TypeError, ValueError):
                continue
            w.write(_json.dumps({"tag": f"{mode}/{tag}", "step": step,
                                 "value": v}) + "\n")
        w.flush()

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_train_batch_end(self, step, logs=None):
        self._train_step += 1
        self._log("train", self._train_step, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._log("train-epoch", epoch, logs)

    def on_eval_end(self, logs=None):
        self._log("eval", self._train_step, logs)

    def on_train_end(self, logs=None):
        for w in self._writers.values():
            w.close()
        self._writers.clear()

    @staticmethod
    def read_scalars(log_dir: str, mode: str = "train"):
        """Load logged scalars back: {tag: [(step, value), ...]}."""
        import json as _json
        import os
        out = {}
        path = os.path.join(log_dir, f"scalars-{mode}.jsonl")
        if not os.path.exists(path):
            return out
        with open(path) as f:
            for line in f:
                rec = _json.loads(line)
                out.setdefault(rec["tag"], []).append(
                    (rec["step"], rec["value"]))
        return out
