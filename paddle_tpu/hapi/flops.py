"""Model FLOPs counting (reference: python/paddle/hapi/dynamic_flops.py —
paddle.flops). Instead of per-layer hook formulas, the count comes from
the XLA cost analysis of the traced forward: exact for any model the
compiler can lower, including custom layers the reference's table-driven
counter misses."""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np

from ..nn.layer import Layer, functional_call, functional_state
from ..tensor import Tensor


def flops(net: Layer, input_size: Sequence[int], custom_ops=None,
          print_detail: bool = False, dtype="float32") -> int:
    """FLOPs of one forward pass at ``input_size`` (leading batch dim
    included). Signature follows the reference paddle.flops(net,
    input_size, custom_ops, print_detail); ``custom_ops`` is accepted
    for compatibility but unused — the count comes from XLA cost
    analysis, which already covers custom layers."""
    from ..core.dtype import convert_dtype

    state = functional_state(net)
    sds = jax.ShapeDtypeStruct(tuple(input_size), convert_dtype(dtype))

    def fwd(params, x):
        return functional_call(
            net, {"params": params, "buffers": state["buffers"]},
            Tensor(x), training=False)

    lowered = jax.jit(fwd).lower(state["params"], sds)
    cost = lowered.compile().cost_analysis()
    if not cost or "flops" not in cost:
        raise RuntimeError(
            "XLA cost analysis returned no FLOPs for this model/backend")
    total = int(cost["flops"])
    if print_detail:
        n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
        print(f"Total Flops: {total}     Total Params: {n_params}")
    return total
