"""High-level Model API: fit/evaluate/predict.

Reference parity: python/paddle/hapi/model.py:883 Model (prepare, fit,
evaluate, predict, save/load, summary) + model_summary.py. The training
loop drives the fused jit TrainStep, so Model.fit gets single-launch steps
for free.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from .. import dispatch
from ..framework.io import load as fload, save as fsave
from ..io.dataloader import DataLoader
from ..io.dataset import Dataset
from ..metric import Metric
from ..nn.layer import Layer
from ..tensor import Tensor
from .callbacks import CallbackList, ModelCheckpoint, ProgBarLogger

F = dispatch.wrapped_ops


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._train_step = None

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]

    # -- core loops -----------------------------------------------------------

    def _build_train_step(self):
        from ..jit import TrainStep

        loss_fn = self._loss

        def step_fn(model, batch):
            *xs, y = batch if isinstance(batch, (list, tuple)) else (batch,)
            out = model(*xs)
            return loss_fn(out, y)

        return TrainStep(self.network, self._optimizer, step_fn)

    def train_batch(self, inputs, labels=None):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if labels is not None and not isinstance(labels, (list, tuple)):
            labels = [labels]
        batch = tuple(inputs) + tuple(labels or ())
        if self._train_step is None:
            self._train_step = self._build_train_step()
        loss = self._train_step(batch)
        return [float(np.asarray(loss))]

    def eval_batch(self, inputs, labels=None):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self.network.eval()
        out = self.network(*[_as_tensor(i) for i in inputs])
        losses = []
        if self._loss is not None and labels is not None:
            label = labels[0] if isinstance(labels, (list, tuple)) else \
                labels
            losses = [float(np.asarray(
                (self._loss(out, _as_tensor(label))).numpy()))]
        self.network.train()
        return losses, out

    def predict_batch(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self.network.eval()
        out = self.network(*[_as_tensor(i) for i in inputs])
        self.network.train()
        return [np.asarray(o.numpy() if isinstance(o, Tensor) else o)
                for o in _leaves(out)]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None):
        train_loader = _as_loader(train_data, batch_size, shuffle,
                                  drop_last, num_workers)
        eval_loader = _as_loader(eval_data, batch_size, False, False,
                                 num_workers) if eval_data is not None \
            else None

        cbks = list(callbacks or [])
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbk = CallbackList(cbks)
        cbk.set_model(self)
        steps = None
        try:
            steps = len(train_loader)
        except Exception:
            pass
        cbk.set_params({"epochs": epochs, "steps": steps,
                        "verbose": verbose, "metrics": ["loss"] + [
                            m.name() for m in self._metrics]})

        cbk.on_train_begin()
        self.stop_training = False
        for epoch in range(epochs):
            cbk.on_epoch_begin(epoch)
            logs = {}
            for step, batch in enumerate(train_loader):
                cbk.on_train_batch_begin(step)
                xs, y = _split_batch(batch)
                losses = self.train_batch(xs, y)
                logs = {"loss": losses[0]}
                for m in self._metrics:
                    if self._train_step is not None:
                        pass  # metric update on eval path
                cbk.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate_loop(eval_loader, cbk)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbk.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        # sync jitted weights back into the eager network
        if self._train_step is not None:
            self._train_step.sync_to_model()
        cbk.on_train_end(logs if "logs" in dir() else None)

    def evaluate_loop(self, loader, cbk=None):
        if cbk is None:
            cbk = CallbackList([])
        if self._train_step is not None:
            self._train_step.sync_to_model()
        cbk.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            cbk.on_eval_batch_begin(step)
            xs, y = _split_batch(batch)
            batch_losses, out = self.eval_batch(xs, y)
            losses.extend(batch_losses)
            for m in self._metrics:
                label = y[0] if isinstance(y, (list, tuple)) else y
                res = m.compute(out, label)
                m.update(res)
            cbk.on_eval_batch_end(step)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            acc = m.accumulate()
            accs = acc if isinstance(acc, (list, tuple)) else [acc]
            logs.update(dict(zip(names, accs)))
        cbk.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = _as_loader(eval_data, batch_size, False, False,
                            num_workers)
        return self.evaluate_loop(loader)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None):
        loader = _as_loader(test_data, batch_size, False, False,
                            num_workers)
        outputs = []
        for batch in loader:
            # datasets that yield (x, label) pairs: feed x only, matching
            # fit/evaluate's split
            xs, _ = _split_batch(batch)
            outputs.append(self.predict_batch(xs))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence ----------------------------------------------------------

    def save(self, path: str, training: bool = True) -> None:
        if self._train_step is not None:
            self._train_step.sync_to_model()
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch=False, reset_optimizer=False
             ) -> None:
        self.network.set_state_dict(fload(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None and
                os.path.exists(opt_path)):
            self._optimizer.set_state_dict(fload(opt_path))
        self._train_step = None  # rebuild against loaded weights

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        total = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        trainable = sum(int(np.prod(p.shape))
                        for p in self.network.parameters() if p.trainable)
        lines = [f"{'Layer':<40}{'Params':>12}"]
        for name, layer in self.network.named_sublayers():
            n = sum(int(np.prod(p.shape))
                    for p in layer._parameters.values() if p is not None)
            if n:
                lines.append(f"{name:<40}{n:>12,}")
        lines.append(f"Total params: {total:,}")
        lines.append(f"Trainable params: {trainable:,}")
        print("\n".join(lines))
        return {"total_params": total, "trainable_params": trainable}


def _as_tensor(x):
    if isinstance(x, Tensor):
        return x
    from ..tensor import to_tensor
    return to_tensor(np.asarray(x))


def _leaves(out):
    import jax
    return jax.tree_util.tree_leaves(
        out, is_leaf=lambda t: isinstance(t, Tensor))


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if data is None or isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    return data


def _split_batch(batch, labeled=True):
    if isinstance(batch, (list, tuple)):
        if labeled and len(batch) >= 2:
            return list(batch[:-1]), batch[-1]
        return list(batch), None
    return [batch], None
