"""Transformer layers.

Reference parity: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoderLayer/Encoder, TransformerDecoderLayer/Decoder,
Transformer). The attention core routes through
scaled_dot_product_attention, which picks the Pallas flash kernel on TPU.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import dispatch
from ..tensor import Tensor
from .common import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm import LayerNorm

F = dispatch.wrapped_ops


class MultiHeadAttention(Layer):
    """Multi-head attention with optional kv caching
    (reference: nn/layer/transformer.py MultiHeadAttention, incl. its
    Cache/StaticCache namedtuples for incremental decode)."""

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        # separate q/k/v projections (reference parity). A compute-time
        # fused [E,3E] matmul was measured NEUTRAL on the BERT-base
        # body step (202.8 vs 202.6 ms, r4) — XLA already extracts the
        # shared-operand read — so the simpler form stays.
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, S, E] -> [B, S, H, D]
        b, s = x.shape[0], x.shape[1]
        return F["reshape"](x, (b, s, self.num_heads, self.head_dim))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache: Optional["MultiHeadAttention.Cache"] = None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._shape(self.q_proj(query))
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value))
        if cache is not None:
            k = F["concat"]([cache.k, k], axis=1)
            v = F["concat"]([cache.v, v], axis=1)
            cache = MultiHeadAttention.Cache(k, v)
        out = F["scaled_dot_product_attention"](
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = F["reshape"](out, (b, s, self.embed_dim))
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out

    def gen_cache(self, key, value=None, type=None):  # noqa: A002
        if value is None:
            # incremental decode: start with empty cache
            import jax.numpy as jnp
            b = key.shape[0]
            empty = jnp.zeros((b, 0, self.num_heads, self.head_dim),
                              dtype=key.dtype if hasattr(key, "dtype")
                              else "float32")
            return MultiHeadAttention.Cache(Tensor(empty), Tensor(empty))
        k = self._shape(self.k_proj(key))
        v = self._shape(self.v_proj(value))
        return MultiHeadAttention.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(
            d_model=d_model, nhead=nhead, dim_feedforward=dim_feedforward,
            dropout=dropout, activation=activation,
            attn_dropout=attn_dropout, act_dropout=act_dropout,
            normalize_before=normalize_before, weight_attr=weight_attr,
            bias_attr=bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            attn_out = self.self_attn(src, src, src, attn_mask=src_mask)
        else:
            attn_out, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(attn_out)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        act = F[self.activation](self.linear1(src))
        src = residual + self.dropout2(self.linear2(self.dropout(act)))
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, new_c = mod(output, src_mask, cache[i])
                new_caches.append(new_c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = dict(
            d_model=d_model, nhead=nhead, dim_feedforward=dim_feedforward,
            dropout=dropout, activation=activation,
            attn_dropout=attn_dropout, act_dropout=act_dropout,
            normalize_before=normalize_before, weight_attr=weight_attr,
            bias_attr=bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt2 = self.self_attn(tgt, tgt, tgt, tgt_mask)
            new_cache = None
        else:
            tgt2, new_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                             cache[0])
        tgt = residual + self.dropout1(tgt2)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt2 = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt2)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        act = F[self.activation](self.linear1(tgt))
        tgt = residual + self.dropout3(self.linear2(self.dropout(act)))
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (new_cache,))

    def gen_cache(self, memory):
        return (self.self_attn.gen_cache(memory),)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_c = mod(output, memory, tgt_mask, memory_mask,
                                    cache[i])
                new_caches.append(new_c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp
        mask = jnp.where(
            jnp.tril(jnp.ones((length, length), dtype=bool)), 0.0,
            -jnp.inf).astype(jnp.float32)
        return Tensor(mask)


def _clone_layer(layer: Layer) -> Layer:
    """Re-instantiate a layer with the same config but freshly drawn
    parameters (the reference re-instantiates from config in
    TransformerEncoder rather than deep-copying weights)."""
    cfg = getattr(layer, "_config", None)
    if cfg is not None:
        return type(layer)(**cfg)
    import copy
    return copy.deepcopy(layer)
