"""Common layers: Linear, Embedding, Dropout, padding, upsampling.

Reference parity: python/paddle/nn/layer/common.py.
"""

from __future__ import annotations

from typing import Optional

from .. import dispatch
from ..tensor import Tensor
from .initializer import get_initializer
from .layer import Layer

F = dispatch.wrapped_ops


class Linear(Layer):
    """y = x @ W + b, W: [in_features, out_features]
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if weight_attr is None else None)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_features,), is_bias=True,
                                              attr=bias_attr)

    def forward(self, x):
        return F["linear"](x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Identity(Layer):
    def forward(self, x):
        return x


class Embedding(Layer):
    """Lookup table (reference: nn/layer/common.py Embedding)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        init = None
        if weight_attr is None:
            init = get_initializer("normal")
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=init)

    def forward(self, x):
        return F["embedding"](x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p: float = 0.5, axis=None,
                 mode: str = "upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F["dropout"](x, p=self.p, training=self.training,
                            mode=self.mode, axis=self.axis)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p: float = 0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F["dropout2d"](x, p=self.p, training=self.training,
                              data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p: float = 0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F["alpha_dropout"](x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return F["flatten"](x, self.start_axis, self.stop_axis)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL"):
        super().__init__()
        self._pad = padding if isinstance(padding, (list, tuple)) else \
            [padding, padding]
        self._mode, self._value, self._fmt = mode, value, data_format

    def forward(self, x):
        return F["pad"](x, self._pad, mode=self._mode, value=self._value,
                        data_format=self._fmt)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW"):
        super().__init__()
        self._pad = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 4
        self._mode, self._value, self._fmt = mode, value, data_format

    def forward(self, x):
        return F["pad"](x, self._pad, mode=self._mode, value=self._value,
                        data_format=self._fmt)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F["interpolate"](x, self.size, self.scale_factor, self.mode,
                                self.align_corners, self.align_mode,
                                self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "nearest",
                         align_corners=False, data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "bilinear",
                         align_corners=True, data_format=data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor: int, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F["pixel_shuffle"](x, self.upscale_factor, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), is_bias=True, attr=bias_attr)

    def forward(self, x1, x2):
        return F["bilinear"](x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F["cosine_similarity"](x1, x2, axis=self.axis, eps=self.eps)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW"):
        super().__init__()
        self._pad = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 6
        self._mode, self._value, self._fmt = mode, value, data_format

    def forward(self, x):
        return F["pad3d"](x, self._pad, mode=self._mode, value=self._value,
                          data_format=self._fmt)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW"):
        super().__init__()
        self._pad = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 4
        self._fmt = data_format

    def forward(self, x):
        return F["zeropad2d"](x, self._pad, data_format=self._fmt)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        k, s, p, d = self._args
        return F["unfold"](x, k, strides=s, paddings=p, dilations=d)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        o, k, s, p, d = self._args
        return F["fold"](x, o, k, strides=s, paddings=p, dilations=d)


class Dropout3D(Layer):
    """Channel-wise 3-D dropout (reference: paddle.nn.Dropout3D)."""

    def __init__(self, p: float = 0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F["dropout3d"](x, p=self.p, training=self.training,
                              data_format=self.data_format)


class PairwiseDistance(Layer):
    """p-norm distance between row pairs (reference:
    paddle.nn.PairwiseDistance, operators/dist_op)."""

    def __init__(self, p: float = 2.0, epsilon: float = 1e-6,
                 keepdim: bool = False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        d = F["add"](F["subtract"](x, y), self.epsilon)
        return F["norm"](d, p=self.p, axis=-1, keepdim=self.keepdim)
