"""Weight initializers.

Reference parity: python/paddle/nn/initializer/ (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign) backed by fluid/initializer.py.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key


def _fans(shape: Tuple[int, ...]):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out, in, *spatial] (reference fan computation)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = jnp.asarray(np.asarray(self.value), dtype=dtype)
        return arr.reshape(shape)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        sample_dtype = dtype if jnp.issubdtype(dtype, jnp.floating) else \
            jnp.float32
        return (self.mean + self.std * jax.random.normal(
            next_key(), shape, dtype=jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        out = jax.random.truncated_normal(next_key(), -2.0, 2.0, shape,
                                          dtype=jnp.float32)
        return (self.mean + self.std * out).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_key(), shape, dtype=jnp.float32,
                                  minval=self.low,
                                  maxval=self.high).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in: Optional[int] = None,
                 fan_out: Optional[int] = None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(tuple(shape))
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in: Optional[int] = None,
                 fan_out: Optional[int] = None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(tuple(shape))
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (std * jax.random.normal(next_key(), shape,
                                        dtype=jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in: Optional[int] = None,
                 negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _gain(self):
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        return math.sqrt(2.0)

    def __call__(self, shape, dtype):
        fi, _ = _fans(tuple(shape))
        fi = self.fan_in if self.fan_in is not None else fi
        limit = self._gain() * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(KaimingUniform):
    def __call__(self, shape, dtype):
        fi, _ = _fans(tuple(shape))
        fi = self.fan_in if self.fan_in is not None else fi
        std = self._gain() / math.sqrt(fi)
        return (std * jax.random.normal(next_key(), shape,
                                        dtype=jnp.float32)).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return (self.gain * jax.random.orthogonal(
            next_key(), shape[0], shape=(),
        )).astype(dtype) if len(shape) == 1 else (
            self.gain * jax.nn.initializers.orthogonal()(
                next_key(), shape, jnp.float32)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                idx = (g * (oc // self.groups) + i, i, *centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype=dtype)


_REGISTRY = {
    "zeros": lambda: Constant(0.0),
    "ones": lambda: Constant(1.0),
    "constant": Constant,
    "normal": Normal,
    "truncated_normal": TruncatedNormal,
    "uniform": Uniform,
    "xavier_uniform": XavierUniform,
    "xavier_normal": XavierNormal,
    "kaiming_uniform": KaimingUniform,
    "kaiming_normal": KaimingNormal,
    "orthogonal": Orthogonal,
}


def get_initializer(spec) -> Initializer:
    if isinstance(spec, Initializer):
        return spec
    if callable(spec):
        return spec
    if isinstance(spec, (int, float)):
        return Constant(float(spec))
    if isinstance(spec, str) and spec in _REGISTRY:
        return _REGISTRY[spec]()
    from ..core.enforce import InvalidArgumentError
    raise InvalidArgumentError(f"Unknown initializer {spec!r}")


class ParamAttr:
    """Parameter attribute bundle (reference: fluid/param_attr.py)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = (get_initializer(initializer)
                            if initializer is not None else None)
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


class Bilinear(Initializer):
    """Bilinear upsampling initializer for transposed-conv weights
    (reference: paddle.nn.initializer.Bilinear,
    fluid/initializer.py BilinearInitializer)."""

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) != 4:
            from ..core.enforce import InvalidArgumentError
            raise InvalidArgumentError(
                "Bilinear initializer needs a 4-D weight [c_out, c_in, "
                f"kh, kw], got {shape}")
        c_out, c_in, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        cw = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        yy = np.arange(kh)[:, None]
        xx = np.arange(kw)[None, :]
        filt = ((1 - np.abs(yy / f_h - ch)) *
                (1 - np.abs(xx / f_w - cw))).astype("float32")
        # every [c_out, c_in] plane gets the filter (reference
        # BilinearInitializer tiles the interpolation kernel across all
        # channel pairs)
        w = np.broadcast_to(filt, shape).copy().astype("float32")
        import jax.numpy as jnp
        return jnp.asarray(w, dtype=dtype)


_REGISTRY["bilinear"] = Bilinear

_global_initializer = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None) -> None:
    """reference: paddle.nn.initializer.set_global_initializer
    (fluid/initializer.py:964) — overrides the default initializer used
    when a layer creates parameters without an explicit one. Pass None to
    restore the built-in defaults."""
    _global_initializer["weight"] = (
        get_initializer(weight_init) if weight_init is not None else None)
    _global_initializer["bias"] = (
        get_initializer(bias_init) if bias_init is not None else None)


def global_initializer(is_bias: bool):
    return _global_initializer["bias" if is_bias else "weight"]


_abstract_init = {"on": False}


class _AbstractInit(Initializer):
    """Shape-only initializer: returns a jax.ShapeDtypeStruct instead of
    allocating a buffer. Used by abstract_init() so billion-parameter
    models can be built for AOT lowering / memory analysis without ever
    materializing weights (the TPU analog of building a ProgramDesc
    without running startup_program — reference: fluid/framework.py's
    separate startup/main programs)."""

    def __call__(self, shape, dtype):
        return jax.ShapeDtypeStruct(tuple(int(d) for d in shape),
                                    jnp.dtype(dtype))


import contextlib


@contextlib.contextmanager
def abstract_init():
    """Within this context every parameter a Layer creates is a
    ShapeDtypeStruct (no device/host memory). The resulting model can't
    run eagerly, but functional_state() yields an abstract pytree that
    jax.jit(...).lower() accepts for AOT compilation against any
    topology."""
    prev = _abstract_init["on"]
    _abstract_init["on"] = True
    try:
        yield
    finally:
        _abstract_init["on"] = prev


def resolve_initializer(init, attr=None, is_bias: bool = False):
    """One resolution chain for parameter initializers, shared by
    Layer.create_parameter and the free paddle.create_parameter:
    explicit attr.initializer > explicit init > global override >
    built-in default (xavier_uniform / zeros)."""
    if _abstract_init["on"]:
        return _AbstractInit()
    if attr is not None and getattr(attr, "initializer", None) is not None:
        init = attr.initializer
    if init is None:
        return global_initializer(is_bias) or get_initializer(
            "zeros" if is_bias else "xavier_uniform")
    if isinstance(init, Initializer) or callable(init):
        return init
    return get_initializer(init)
