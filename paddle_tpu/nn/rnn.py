"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py —
SimpleRNN/LSTM/GRU + cells)."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import dispatch
from ..tensor import Tensor
from .initializer import Uniform
from .layer import Layer

F = dispatch.wrapped_ops

_GATES = {"SimpleRNN": 1, "LSTM": 4, "GRU": 3}


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if direction in ("bidirect",
                                                 "bidirectional") else 1
        g = _GATES[mode]
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._weight_names = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_size = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                suffix = f"l{layer}" + ("_reverse" if d == 1 else "")
                names = [f"weight_ih_{suffix}", f"weight_hh_{suffix}",
                         f"bias_ih_{suffix}", f"bias_hh_{suffix}"]
                shapes = [(g * hidden_size, in_size),
                          (g * hidden_size, hidden_size),
                          (g * hidden_size,), (g * hidden_size,)]
                for n, s in zip(names, shapes):
                    self.add_parameter(n, self.create_parameter(
                        s, default_initializer=init))
                self._weight_names.extend(names)

    def _weights(self):
        return [self._parameters[n] for n in self._weight_names]

    def forward(self, inputs, initial_states=None):
        batch_axis = 1 if self.time_major else 0
        b = inputs.shape[batch_axis]
        n = self.num_layers * self.num_directions
        if initial_states is None:
            zero = F["zeros"]((n, b, self.hidden_size),
                              dtype=str(inputs.dtype))
            initial_states = (zero, zero) if self.mode == "LSTM" else zero
        out, states = F["rnn"](inputs, initial_states, self._weights(),
                               mode=self.mode, num_layers=self.num_layers,
                               direction=self.direction,
                               activation=self.activation,
                               time_major=self.time_major)
        return out, states

    def extra_repr(self):
        return (f"{self.input_size}, {self.hidden_size}, "
                f"num_layers={self.num_layers}")


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("SimpleRNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class _CellBase(Layer):
    def __init__(self, mode, input_size, hidden_size):
        super().__init__()
        g = _GATES[mode]
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.weight_ih = self.create_parameter((g * hidden_size, input_size),
                                               default_initializer=init)
        self.weight_hh = self.create_parameter((g * hidden_size, hidden_size),
                                               default_initializer=init)
        self.bias_ih = self.create_parameter((g * hidden_size,), is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter((g * hidden_size,), is_bias=True,
                                             default_initializer=init)


class SimpleRNNCell(_CellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kwargs):
        super().__init__("SimpleRNN", input_size, hidden_size)
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = F["zeros"]((inputs.shape[0], self.hidden_size),
                                dtype=str(inputs.dtype))
        h = F["simple_rnn_cell"](inputs, states, self.weight_ih,
                                 self.weight_hh, self.bias_ih, self.bias_hh,
                                 activation=self.activation)
        return h, h


class LSTMCell(_CellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__("LSTM", input_size, hidden_size)

    def forward(self, inputs, states=None):
        if states is None:
            z = F["zeros"]((inputs.shape[0], self.hidden_size),
                           dtype=str(inputs.dtype))
            states = (z, z)
        h, c = states
        h_new, c_new = F["lstm_cell"](inputs, h, c, self.weight_ih,
                                      self.weight_hh, self.bias_ih,
                                      self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(_CellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__("GRU", input_size, hidden_size)

    def forward(self, inputs, states=None):
        if states is None:
            states = F["zeros"]((inputs.shape[0], self.hidden_size),
                                dtype=str(inputs.dtype))
        h = F["gru_cell"](inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wrap a cell into a recurrence over time (reference: nn/layer/rnn.py
    RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import jax
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        lens = None
        if sequence_length is not None:
            lens = sequence_length.value if isinstance(
                sequence_length, Tensor) else jnp.asarray(sequence_length)
        states = initial_states
        outs = []
        for t in order:
            xt = inputs[:, t] if time_axis == 1 else inputs[t]
            out, new_states = self.cell(xt, states)
            if lens is not None:
                # freeze state and zero the output beyond each row's valid
                # length (reference RNN wrapper masking with
                # sequence_length): final_states land on step len-1
                valid = (lens > t)

                def _mask(new, old):
                    nv = new.value if isinstance(new, Tensor) else new
                    m = valid.reshape((-1,) + (1,) * (nv.ndim - 1))
                    if old is None:
                        return Tensor(jnp.where(m, nv, jnp.zeros_like(nv)))
                    ov = old.value if isinstance(old, Tensor) else old
                    return Tensor(jnp.where(m, nv, ov))

                if states is None:
                    new_states = jax.tree_util.tree_map(
                        lambda n: _mask(n, None), new_states,
                        is_leaf=lambda x: isinstance(x, Tensor))
                else:
                    new_states = jax.tree_util.tree_map(
                        _mask, new_states, states,
                        is_leaf=lambda x: isinstance(x, Tensor))
                om = valid.reshape((-1,) + (1,) * (out.ndim - 1))
                out = Tensor(jnp.where(
                    om, out.value if isinstance(out, Tensor) else out, 0))
            states = new_states
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        stacked = F["stack"](outs, axis=time_axis)
        return stacked, states


class RNNCellBase(Layer):
    """Base class for user-defined recurrent cells (reference:
    paddle.nn.RNNCellBase, nn/layer/rnn.py). Subclasses implement
    ``forward(inputs, states) -> (output, new_states)``; this base supplies
    zero-filled initial states from ``state_shape``."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        shape = shape if shape is not None else self.state_shape
        batch = batch_ref.shape[batch_dim_idx]

        def one(s):
            full = (batch,) + tuple(int(d) for d in s)
            return F["full"](full, init_value,
                             dtype or str(batch_ref.dtype))

        if isinstance(shape, (list, tuple)) and shape and \
                isinstance(shape[0], (list, tuple)):
            return type(shape)(one(s) for s in shape)
        return one(shape)

    @property
    def state_shape(self):
        raise NotImplementedError(
            "RNNCellBase subclasses must define state_shape or override "
            "get_initial_states")


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference: paddle.nn.BiRNN):
    runs cell_fw forward and cell_bw reversed, concatenating outputs on
    the feature axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.time_major = time_major
        # cells are registered once, through the wrapping RNNs (registering
        # them directly too would duplicate every parameter)
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    @property
    def cell_fw(self):
        return self.rnn_fw.cell

    @property
    def cell_bw(self):
        return self.rnn_bw.cell

    def forward(self, inputs, initial_states=None, sequence_length=None):
        fw_init, bw_init = (initial_states if initial_states is not None
                            else (None, None))
        # RNN's sequence_length masking freezes states outside each row's
        # valid window in BOTH directions: the reverse pass walks t from
        # maxlen-1 down, keeping the initial state until it enters the
        # valid prefix, so padding never contaminates states or outputs.
        out_fw, st_fw = self.rnn_fw(inputs, fw_init, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, bw_init, sequence_length)
        return F["concat"]([out_fw, out_bw], axis=-1), (st_fw, st_bw)
