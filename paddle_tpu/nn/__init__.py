"""paddle_tpu.nn — layers, containers, initializers, functional API.

Reference parity: python/paddle/nn/.
"""

from . import functional
from . import initializer
from . import utils
from .activation import (CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid,
                         Hardswish, Hardtanh, LeakyReLU, LogSigmoid,
                         LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, SELU,
                         Sigmoid, Silu, Softmax, Softplus, Softshrink,
                         Softsign, Swish, Tanh, Tanhshrink,
                         ThresholdedReLU)
from .common import (AlphaDropout, Bilinear, CosineSimilarity, Dropout,
                     Dropout2D, Dropout3D, Embedding, Flatten, Identity,
                     Linear, Pad1D, PairwiseDistance,
                     Pad2D, Pad3D, PixelShuffle, Upsample,
                     UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
                     Unfold, Fold)
from .container import LayerDict, LayerList, ParameterList, Sequential
from .conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
                   Conv3DTranspose, DeformConv2D)
from .initializer import ParamAttr
from .layer import (Layer, bind_state, functional_call, functional_state)
from .loss import (BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss,
                   CrossEntropyLoss, CTCLoss, HingeEmbeddingLoss,
                   HSigmoidLoss, KLDivLoss, L1Loss, MarginRankingLoss,
                   MSELoss, MultiLabelSoftMarginLoss, NLLLoss,
                   PoissonNLLLoss, GaussianNLLLoss, SmoothL1Loss,
                   SoftMarginLoss, TripletMarginLoss)
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                   DataNorm,
                   GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                   LayerNorm, LocalResponseNorm, RMSNorm, SpectralNorm,
                   SyncBatchNorm)
from .pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                      AdaptiveMaxPool1D, AdaptiveMaxPool2D,
                      AdaptiveMaxPool3D, AvgPool1D,
                      AvgPool2D, AvgPool3D, LPPool2D, MaxPool1D,
                      MaxPool2D, MaxPool3D)
from .rnn import (BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase,
                  SimpleRNN, SimpleRNNCell)
from .decode import BeamSearchDecoder, dynamic_decode
# grad-clip classes are exported from paddle.nn in the reference too
from ..optimizer.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                              ClipGradByValue)
from .transformer import (MultiHeadAttention, Transformer,
                          TransformerDecoder, TransformerDecoderLayer,
                          TransformerEncoder, TransformerEncoderLayer)
