"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import dispatch
from .layer import Layer

F = dispatch.wrapped_ops


class _Act(Layer):
    _op = ""
    _kwargs: dict = {}

    def __init__(self, name=None, **kwargs):
        super().__init__()
        self._extra = {**self._kwargs, **kwargs}

    def forward(self, x):
        return F[self._op](x, **self._extra)

    def extra_repr(self):
        return ", ".join(f"{k}={v}" for k, v in self._extra.items())


class ReLU(_Act):
    _op = "relu"


class ReLU6(_Act):
    _op = "relu6"


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F["leaky_relu"](x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 name=None, data_format="NCHW"):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=lambda s, d: __import__(
                "jax.numpy", fromlist=["full"]).full(s, init, d))

    def forward(self, x):
        return F["prelu"](x, self.weight)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F["elu"](x, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F["celu"](x, self.alpha)


class SELU(_Act):
    _op = "selu"


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F["gelu"](x, self.approximate)


class Silu(_Act):
    _op = "silu"


class Swish(_Act):
    _op = "swish"


class Mish(_Act):
    _op = "mish"


class Sigmoid(_Act):
    _op = "sigmoid"


class LogSigmoid(_Act):
    _op = "log_sigmoid"


class Hardsigmoid(_Act):
    _op = "hardsigmoid"


class Hardswish(_Act):
    _op = "hardswish"


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F["hardtanh"](x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F["hardshrink"](x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F["softshrink"](x, self.threshold)


class Tanhshrink(_Act):
    _op = "tanhshrink"


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F["softplus"](x, self.beta, self.threshold)


class Softsign(_Act):
    _op = "softsign"


class Tanh(_Act):
    _op = "tanh"


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F["softmax"](x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F["log_softmax"](x, self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F["maxout"](x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F["glu"](x, self.axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F["thresholded_relu"](x, self.threshold)
