"""paddle_tpu.nn.functional — eager-wrapped functional API.

Reference parity: python/paddle/nn/functional/. Every function here is the
autograd-aware wrapped version of the pure kernel in paddle_tpu.ops.
"""

from .. import dispatch as _dispatch

_NN_OPS = [
    # activations
    "relu", "relu6", "leaky_relu", "prelu", "rrelu", "elu", "selu", "celu",
    "gelu", "silu", "swish", "mish", "sigmoid", "log_sigmoid", "hardsigmoid",
    "hardswish", "hardtanh", "hardshrink", "softshrink", "tanhshrink",
    "softplus", "softsign", "tanh", "softmax", "log_softmax",
    "gumbel_softmax", "maxout", "glu",
    # linear/embedding/common
    "linear", "embedding", "one_hot", "bilinear", "dropout", "dropout2d",
    "dropout3d", "alpha_dropout", "label_smooth", "cosine_similarity",
    "normalize", "sequence_mask", "pad", "interpolate", "upsample",
    "pixel_shuffle", "pixel_unshuffle", "unfold", "fold", "grid_sample",
    "affine_grid", "temporal_shift", "channel_shuffle", "pad3d",
    "zeropad2d", "thresholded_relu",
    # conv
    "conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
    "conv3d_transpose", "deformable_conv",
    # vision/CTR extras (ops/vision_extra.py)
    "affine_channel", "space_to_depth", "shuffle_channel", "cvm",
    "shuffle_batch", "partial_concat", "partial_sum", "batch_fc",
    "row_conv", "conv_shift", "im2sequence", "add_position_encoding",
    "fsp", "bilinear_tensor_product", "correlation", "max_unpool2d",
    "spp", "psroi_pool", "prroi_pool", "yolov3_loss",
    # pooling
    "max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d", "avg_pool2d",
    "avg_pool3d", "lp_pool2d", "adaptive_avg_pool1d",
    "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
    "adaptive_max_pool2d", "adaptive_max_pool3d",
    # norm
    "layer_norm", "rms_norm", "batch_norm", "instance_norm", "group_norm",
    "local_response_norm",
    # attention
    "scaled_dot_product_attention",
    # losses
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
    "hinge_embedding_loss", "cosine_embedding_loss", "triplet_margin_loss",
    "square_error_cost", "log_loss", "sigmoid_focal_loss",
    "soft_margin_loss", "multi_label_soft_margin_loss",
    "poisson_nll_loss", "gaussian_nll_loss",
    # extended loss family (ops/loss_extra.py)
    "hinge_loss", "huber_loss", "modified_huber_loss", "rank_loss",
    "margin_rank_loss", "bpr_loss", "teacher_student_sigmoid_loss",
    "squared_l2_distance", "squared_l2_norm", "l1_norm", "cos_sim",
    "dice_loss", "npair_loss", "center_loss", "ctc_loss", "nce",
    "hsigmoid_loss", "sample_logits", "bce_loss", "kldiv_loss",
    # decode / misc
    "gather_tree", "diag_embed",
]

for _name in _NN_OPS:
    globals()[_name] = _dispatch.wrapped_ops[_name]

del _name


def _inplace(name):
    def f(x, *args, **kwargs):
        out = _dispatch.wrapped_ops[name](x, *args, **kwargs)
        return x._inplace_assign(out) if hasattr(x, "_inplace_assign") \
            else out
    f.__name__ = name + "_"
    f.__doc__ = f"In-place variant of {name} (reference: F.{name}_)."
    return f


relu_ = _inplace("relu")
elu_ = _inplace("elu")
tanh_ = _inplace("tanh")
softmax_ = _inplace("softmax")
del _inplace
