"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference parity: python/paddle/fluid/layers/rnn.py BeamSearchDecoder /
dynamic_decode (exported as paddle.nn.BeamSearchDecoder,
paddle.nn.dynamic_decode).

TPU-native design: the reference drives a While loop of beam_search +
beam_search_decode ops over LoD tensors; here decoding is a dense
fixed-shape loop over ``ops.decode_extra.beam_search_step`` (top-k over
MXU-friendly [batch*beam, vocab] logits) with the backtrace done by
``gather_tree`` — the whole decode can sit inside one jit when shapes are
static.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from .. import dispatch
from ..ops.decode_extra import beam_search_step, gather_tree
from ..tensor import Tensor
from .layer import Layer

F = dispatch.wrapped_ops

__all__ = ["BeamSearchDecoder", "dynamic_decode", "sample_token",
           "fused_sample_token", "fused_verify_tokens",
           "speculative_verify_tokens", "masked_carry_advance",
           "masked_run_advance", "ngram_draft_tokens"]


# ---------------------------------------------------------------------------
# Shared autoregressive sampler (jit-safe, pure JAX)
# ---------------------------------------------------------------------------

def sample_token(last, temperature: float = 0.0, top_k=None, key=None):
    """ONE sampling semantics for every decode path: greedy argmax at
    ``temperature == 0``, temperature/top-k categorical otherwise.

    ``last``: [B, V] final-position logits; returns ``(tokens [B]
    int32, new_key)``. The jitted whole-generate scan, the chunked
    per-block generate, the continuous-batching engine's prefill and
    decode steps, and the speculative verify step all call THIS
    function, so their token streams provably share one sampler
    (previously the same four lines lived in three places).
    ``temperature``/``top_k`` must be Python statics under jit; ``key``
    is unused (and may be None) on the greedy path."""
    import jax

    if temperature == 0.0:
        return jnp.argmax(last, -1).astype(jnp.int32), key
    scaled = last.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -1e10, scaled)
    key, sub = jax.random.split(key)
    return jax.random.categorical(sub, scaled, axis=-1).astype(
        jnp.int32), key


def masked_carry_advance(nxt, cur, active, emitted, rem, eos):
    """Carry-form sampler update for the device-resident multi-step
    decode loop (r19, models/gpt.py ``multi_step_decode``): fold one
    freshly sampled token batch into the ``(cur, active, emitted)``
    loop carry under the per-slot active mask.

    ``nxt``: [B] int32 tokens this iteration's :func:`sample_token` /
    :func:`fused_sample_token` produced; ``cur``: [B] the previous
    carry tokens; ``active``: [B] bool, which slots are still
    generating; ``emitted``: [B] int32, tokens emitted so far THIS
    macro launch; ``rem``: [B] int32, each slot's remaining emission
    budget (``max_new_tokens - len(generated)`` at the boundary);
    ``eos``: [B] int32 EOS ids (−1 = none — token ids are >= 0, so −1
    never matches).

    Returns ``(cur', active', emitted')``. The stop rule mirrors the
    host engine's ``_finish_due`` exactly — a slot stops after
    emitting EOS or its budget's last token — so an N-step launch's
    per-slot token streams are bit-identical to N host-driven steps.
    A stopped slot keeps its last token in ``cur`` and rides the rest
    of the launch masked (the harness redirects its KV writes to the
    scratch page), exactly like a parked slot in the per-token
    engine."""
    emitted = emitted + active.astype(jnp.int32)
    stop = (nxt == eos) | (emitted >= rem)
    new_active = jnp.logical_and(active, jnp.logical_not(stop))
    return jnp.where(active, nxt, cur), new_active, emitted


def masked_run_advance(run, run_len, cur, active, emitted, rem, eos):
    """Carry-form accept/rewind twin of :func:`masked_carry_advance`
    for per-iteration ACCEPTED RUNS (r22 in-program speculative
    verify, models/gpt.py ``multi_step_decode``): fold a ``[B, W]``
    token run — each slot's accepted draft prefix plus its
    correction/bonus token, ``W = k+1`` — into the ``(cur, active,
    emitted)`` loop carry, truncating each slot's run exactly as the
    host engine would have by emitting it token by token through
    ``_finish_due``:

    - an EOS inside the run ends the emission AT that token (later
      accepted drafts are rewound — they were never emitted);
    - the emission budget ``rem`` caps the total: a run whose last
      token lands exactly on the budget stops the slot there (the
      draft clip ``k_eff = min(k, budget-1)`` guarantees a run never
      OVERSHOOTS the budget, so the cap only ever bites at the run's
      final token — the same invariant the host ``_spec_step`` holds).

    ``run``: [B, W] int32 candidate tokens (positions past
    ``run_len`` are ignored); ``run_len``: [B] int32 in [1, W];
    ``cur``/``active``/``emitted``/``rem``/``eos``: the
    :func:`masked_carry_advance` carries. Returns ``(run_masked
    [B, W] int32 with −1 beyond each slot's emitted share, emit_len
    [B] int32, cur', active', emitted')`` — ``run_masked`` is exactly
    the widened token-ring row the macro program commits for this
    iteration, so the host's drain replays the per-token stream by
    reading it left to right."""
    b, w = run.shape
    run = run.astype(jnp.int32)
    jpos = jnp.arange(w)[None, :]
    in_run = jpos < run_len[:, None]
    budget = jnp.maximum(rem - emitted, 0)
    # first EOS position within the run (w when none): emitting stops
    # AFTER that token, exactly like the host's append-then-check loop
    is_eos = (run == eos[:, None]) & in_run
    eos_idx = jnp.argmax(
        jnp.concatenate([is_eos, jnp.ones((b, 1), bool)], axis=1),
        axis=1)
    emit_len = jnp.minimum(run_len, jnp.minimum(eos_idx + 1, budget))
    emit_len = jnp.where(active, jnp.maximum(emit_len, 0), 0)
    last = jnp.take_along_axis(
        run, jnp.maximum(emit_len - 1, 0)[:, None], axis=1)[:, 0]
    hit_eos = (eos_idx + 1) <= emit_len
    new_emitted = emitted + emit_len
    stop = hit_eos | (new_emitted >= rem)
    new_active = jnp.logical_and(active, jnp.logical_not(stop))
    run_masked = jnp.where(
        (jpos < emit_len[:, None]) & active[:, None], run, -1)
    new_cur = jnp.where(active & (emit_len > 0), last, cur)
    return run_masked, emit_len, new_cur, new_active, new_emitted


def ngram_draft_tokens(hist, hist_len, k: int, max_ngram: int = 3,
                       min_ngram: int = 1):
    """Device twin of inference/speculative.py ``NGramDraft._lookup``
    (r22 in-program drafting): prompt-lookup drafting as pure gathers
    over the slot's stored token history, so the draft runs INSIDE
    the macro decode program with zero host round trips.

    ``hist``: [B, H] int32 token history buffer (prompt + generated,
    right-padded — contents past ``hist_len`` are ignored);
    ``hist_len``: [B] int32 valid lengths. Returns ``[B, k]`` int32
    proposals with EXACTLY the host source's semantics: the longest
    ``max_ngram..min_ngram`` suffix that re-occurs earlier in the
    history (most recent occurrence wins) proposes the k tokens that
    followed it there, clipped continuations pad with their last
    token, and no match at any order repeats the last history token.
    Draft QUALITY is all this affects — greedy verify emission is
    independent of the proposals — so the twin exists to keep
    in-program acceptance rates identical to the host source's, not
    for correctness."""
    b, hcap = hist.shape
    n = hist_len.astype(jnp.int32)
    pos = jnp.arange(hcap)
    last = jnp.take_along_axis(
        hist, jnp.maximum(n - 1, 0)[:, None], axis=1)
    out = jnp.broadcast_to(last, (b, k)).astype(jnp.int32)
    found = jnp.zeros((b,), bool)
    for g in range(max_ngram, min_ngram - 1, -1):
        # host rule: orders above n-1 are skipped (the suffix must
        # leave at least one earlier token to match against)
        g_ok = g <= (n - 1)
        pat_idx = jnp.maximum(n[:, None] - g + jnp.arange(g)[None, :],
                              0)
        pat = jnp.take_along_axis(hist, pat_idx, axis=1)     # [B, g]
        win_idx = jnp.minimum(pos[:, None] + jnp.arange(g)[None, :],
                              hcap - 1)                      # [H, g]
        win = hist[:, win_idx]                               # [B,H,g]
        match = (win == pat[:, None, :]).all(-1)             # [B, H]
        # windows end at e = s+g <= n-1: the suffix itself (ending at
        # n) is excluded, exactly the host's h[:n-1] window view
        valid_s = (pos[None, :] + g) <= (n[:, None] - 1)
        hit = match & valid_s & g_ok[:, None]
        any_hit = hit.any(-1)
        # most recent earlier occurrence wins: the largest start
        s_best = jnp.argmax(jnp.where(hit, pos[None, :], -1), axis=-1)
        e = s_best + g
        cont_idx = jnp.minimum(e[:, None] + jnp.arange(k)[None, :],
                               hcap - 1)
        cont = jnp.take_along_axis(hist, cont_idx, axis=1)   # [B, k]
        clen = jnp.clip(n[:, None] - e[:, None], 1, k)
        cont_last = jnp.take_along_axis(cont, clen - 1, axis=1)
        cont = jnp.where(jnp.arange(k)[None, :] < clen, cont,
                         cont_last)
        take = any_hit & jnp.logical_not(found)
        out = jnp.where(take[:, None], cont, out).astype(jnp.int32)
        found = found | any_hit
    return out


def _head_logits(hidden, weight, bias, transpose_y: bool):
    """The unfused lm_head matmul (models/gpt.py ``logits`` semantics:
    ``hidden @ W.T`` for the tied [V, D] layout, ``hidden @ W`` for the
    untied [D, V] head) — the fallback the fused sampler delegates to
    whenever streaming cannot reproduce the exact unfused behavior."""
    logits = jnp.matmul(hidden, weight.T if transpose_y else weight)
    if bias is not None:
        logits = logits + bias
    return logits


def fused_sample_token(hidden, weight, temperature: float = 0.0,
                       top_k=None, key=None, transpose_y: bool = False,
                       bias=None, tile: int = 2048):
    """:func:`sample_token` twin over FINAL HIDDEN STATES + the lm_head
    weight instead of materialized logits (the r13 fused decode hot
    path): the jitted whole-generate scan, the continuous-batching
    engine's fused prefill/decode steps and the fused speculative
    verify all call THIS function, so their token streams still share
    ONE sampler while the [B, vocab] logits tensor never reaches HBM
    on the paths that can stream it.

    ``hidden``: [B, D]; ``weight``/``transpose_y``/``bias``: the head
    layout (models/gpt.py ``head_params``). Routing:

    - greedy (``temperature == 0``): streaming argmax over vocab tiles
      (ops/pallas/fused_sample.py) — bit-identical tokens to
      ``argmax(logits)`` by the first-index tie rule;
    - ``top_k`` sampling: streaming top-k reservoir, then one
      categorical over the k candidates (the same top-k distribution;
      the [B, V] tensor still never materializes);
    - plain temperature sampling, or an active serving-mesh trace
      (vocab-sharded weights — GSPMD already keeps per-device logits
      tiles, and the tile scan would fight the sharding): the exact
      unfused logits + :func:`sample_token`.

    Returns ``(tokens [B] int32, new_key)`` like ``sample_token``."""
    import jax

    from ..ops.pallas.fused_sample import fused_sample
    from ..ops.pallas.paged_attention import get_head_sharding

    if get_head_sharding() is not None:
        return sample_token(_head_logits(hidden, weight, bias,
                                         transpose_y),
                            temperature, top_k, key)
    if temperature == 0.0:
        return fused_sample(hidden, weight, bias=bias,
                            transpose_y=transpose_y, tile=tile), key
    if top_k is not None:
        vals, idxs = fused_sample(hidden, weight, bias=bias,
                                  transpose_y=transpose_y, top_k=top_k,
                                  tile=tile)
        key, sub = jax.random.split(key)
        pick = jax.random.categorical(
            sub, vals.astype(jnp.float32) / temperature, axis=-1)
        tok = jnp.take_along_axis(idxs, pick[:, None], axis=1)[:, 0]
        return tok.astype(jnp.int32), key
    return sample_token(_head_logits(hidden, weight, bias, transpose_y),
                        temperature, top_k, key)


def fused_verify_tokens(hidden, drafts, weight, temperature: float = 0.0,
                        top_k=None, key=None, transpose_y: bool = False,
                        bias=None, tile: int = 2048):
    """:func:`speculative_verify_tokens` twin over the verify chunk's
    final hidden states [B, s, D]: on the greedy single-device path the
    per-position target tokens come from the STREAMING argmax (one
    fused scoring+acceptance program, no [B, s, V] logits in HBM);
    temperature/top-k verification needs full per-position
    distributions (acceptance probabilities + residual resampling), so
    those — and serving-mesh traces — delegate to the exact unfused
    logits + ``speculative_verify_tokens``. Same return contract."""
    from ..ops.pallas.fused_sample import fused_sample
    from ..ops.pallas.paged_attention import get_head_sharding

    b, s, d = hidden.shape
    if temperature == 0.0 and get_head_sharding() is None:
        full = fused_sample(hidden.reshape(b * s, d), weight, bias=bias,
                            transpose_y=transpose_y, tile=tile)
        full = full.reshape(b, s).astype(jnp.int32)
        accept = drafts.astype(jnp.int32) == full[:, :-1]
        return accept, full[:, :-1], full, key
    return speculative_verify_tokens(
        _head_logits(hidden, weight, bias, transpose_y), drafts,
        temperature, top_k, key)


def speculative_verify_tokens(logits, drafts, temperature: float = 0.0,
                              top_k=None, key=None):
    """Per-position accept/replace decisions for speculative decoding.

    ``logits``: [B, s, V] target-model logits over the verify chunk
    ``[cur, d_0, .., d_{s-2}]`` — position ``j`` scores the token that
    follows ``cur, d_0..d_{j-1}``. ``drafts``: [B, s-1] the draft
    tokens ``d_0..d_{s-2}``. Returns ``(accept [B, s-1] bool,
    resampled [B, s-1] int32, full [B, s] int32, key)``:

    - ``full[:, j]``: the token the target itself would emit at
      position ``j`` (``sample_token`` semantics — argmax when greedy),
      i.e. exactly the vanilla decode token given that prefix;
    - ``accept[:, j]``: whether draft ``d_j`` survives at position
      ``j`` — greedy: exact match against ``full``; temperature: a
      uniform draw under the target probability of ``d_j`` (the
      deterministic-draft acceptance rule, q = point mass);
    - ``resampled[:, j]``: the replacement token if ``j`` is the FIRST
      rejection — greedy: the argmax correction (== ``full``);
      temperature: a sample from the residual distribution (target
      probabilities with the rejected draft token's mass removed and
      renormalized), which keeps the emitted stream distributed
      exactly as the target model.

    The caller takes ``n`` = length of the leading all-accepted prefix
    (over its per-sequence valid draft count) and emits
    ``drafts[:n] + (resampled[n] if n < valid else full[valid])``."""
    import jax

    b, s, _ = logits.shape
    if temperature == 0.0:
        full = jnp.argmax(logits, -1).astype(jnp.int32)
        accept = drafts.astype(jnp.int32) == full[:, :-1]
        return accept, full[:, :-1], full, key
    scaled = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -1e10, scaled)
    probs = jax.nn.softmax(scaled, axis=-1)
    key, k_acc, k_resid, k_full = jax.random.split(key, 4)
    # full-distribution samples at every position (sample_token
    # semantics, batched over positions)
    full = jax.random.categorical(
        k_full, scaled.reshape(b * s, -1), axis=-1).reshape(
        b, s).astype(jnp.int32)
    d32 = drafts.astype(jnp.int32)
    p_draft = jnp.take_along_axis(probs[:, :-1], d32[..., None],
                                  axis=-1)[..., 0]
    u = jax.random.uniform(k_acc, (b, s - 1))
    accept = u < p_draft
    # residual: remove the rejected draft's mass, renormalize (in the
    # log domain: mask the draft token out and re-sample)
    masked = scaled[:, :-1].at[
        jnp.arange(b)[:, None], jnp.arange(s - 1)[None], d32].set(-1e10)
    resampled = jax.random.categorical(
        k_resid, masked.reshape(b * (s - 1), -1), axis=-1).reshape(
        b, s - 1).astype(jnp.int32)
    return accept, resampled, full, key


class BeamSearchDecoder:
    """Beam-search decoder over a recurrent cell (reference:
    fluid/layers/rnn.py BeamSearchDecoder).

    cell: an RNN cell ``(inputs, states) -> (output, new_states)``.
    output_fn: maps cell output -> logits over the vocabulary (e.g. the
    projection layer); defaults to identity.
    embedding_fn: maps token ids -> cell inputs; required unless the cell
    consumes raw ids.
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers --------------------------------------------------------------

    def _merge(self, t):
        v = t.value if isinstance(t, Tensor) else jnp.asarray(t)
        return v.reshape((-1,) + v.shape[2:])  # [B, beam, ...] -> [B*beam]

    def _split(self, v, batch):
        v = v.value if isinstance(v, Tensor) else jnp.asarray(v)
        return v.reshape((batch, self.beam_size) + v.shape[1:])

    def _logits(self, cell_out):
        out = self.output_fn(cell_out) if self.output_fn else cell_out
        return out.value if isinstance(out, Tensor) else jnp.asarray(out)

    def decode(self, initial_states, max_step_num: int):
        """Run the full beam search; returns (ids [B, T], scores [B])."""
        import jax
        # infer batch from the states pytree
        leaves = jax.tree_util.tree_leaves(
            initial_states, is_leaf=lambda t: isinstance(t, Tensor))
        batch = (leaves[0].shape[0] if leaves else 1)

        def tile_state(t):
            v = t.value if isinstance(t, Tensor) else jnp.asarray(t)
            v = jnp.repeat(v[:, None], self.beam_size, axis=1)
            return Tensor(v.reshape((-1,) + v.shape[2:]))

        states = jax.tree_util.tree_map(
            tile_state, initial_states,
            is_leaf=lambda t: isinstance(t, Tensor))

        tokens = jnp.full((batch, self.beam_size), self.start_token,
                          jnp.int32)
        # first expansion starts from one live beam per batch row
        scores = jnp.where(
            jnp.arange(self.beam_size)[None, :] == 0, 0.0, -jnp.inf
        ) * jnp.ones((batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        ids_steps, parent_steps = [], []

        for _ in range(max_step_num):
            flat_tok = Tensor(tokens.reshape(-1))
            inp = self.embedding_fn(flat_tok) if self.embedding_fn \
                else flat_tok
            cell_out, states = self.cell(inp, states)
            logits = self._logits(cell_out)            # [B*beam, V]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(batch, self.beam_size, -1)
            scores, parent, tok = beam_search_step(
                logp, scores, self.beam_size, end_token=self.end_token,
                finished=finished)
            # reorder states along the chosen parents
            flat_parent = (parent +
                           jnp.arange(batch)[:, None] * self.beam_size
                           ).reshape(-1)
            states = jax.tree_util.tree_map(
                lambda t: Tensor(jnp.take(
                    t.value if isinstance(t, Tensor) else jnp.asarray(t),
                    flat_parent, axis=0)),
                states, is_leaf=lambda t: isinstance(t, Tensor))
            finished = jnp.take_along_axis(finished, parent, axis=1) | (
                tok == self.end_token)
            tokens = tok
            ids_steps.append(tok)
            parent_steps.append(parent)
            from jax._src import core as _jc
            if _jc.trace_state_clean() and bool(jnp.all(finished)):
                break  # eager early exit; under jit the loop is static

        ids = jnp.stack(ids_steps)                     # [T, B, beam]
        parents = jnp.stack(parent_steps)
        full = gather_tree(ids, parents)               # [T, B, beam]
        best = jnp.argmax(scores, axis=1)              # [B]
        seq = jnp.take_along_axis(
            full, best[None, :, None], axis=2)[:, :, 0]
        return Tensor(seq.swapaxes(0, 1)), Tensor(
            jnp.max(scores, axis=1))

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """reference helper: repeat batch entries beam_size times."""
        v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]))


def dynamic_decode(decoder, inits=None, max_step_num: int = 100,
                   output_time_major: bool = False, impute_finished=False,
                   is_test: bool = False, return_length: bool = False,
                   **kwargs):
    """Drive a decoder to completion (reference: fluid/layers/rnn.py
    dynamic_decode). Returns (ids, scores) — and lengths when
    ``return_length``."""
    ids, scores = decoder.decode(inits, max_step_num)
    lengths = None
    if return_length:
        v = ids.value  # [B, T] batch-major here, before any transpose
        lengths = jnp.argmax(
            jnp.concatenate(
                [(v == decoder.end_token),
                 jnp.ones_like(v[:, :1], bool)], axis=1), axis=1)
    if output_time_major:
        ids = F["transpose"](ids, [1, 0])
    if return_length:
        return ids, scores, Tensor(lengths.astype(jnp.int32))
    return ids, scores
