"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference parity: python/paddle/fluid/layers/rnn.py BeamSearchDecoder /
dynamic_decode (exported as paddle.nn.BeamSearchDecoder,
paddle.nn.dynamic_decode).

TPU-native design: the reference drives a While loop of beam_search +
beam_search_decode ops over LoD tensors; here decoding is a dense
fixed-shape loop over ``ops.decode_extra.beam_search_step`` (top-k over
MXU-friendly [batch*beam, vocab] logits) with the backtrace done by
``gather_tree`` — the whole decode can sit inside one jit when shapes are
static.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from .. import dispatch
from ..ops.decode_extra import beam_search_step, gather_tree
from ..tensor import Tensor
from .layer import Layer

F = dispatch.wrapped_ops

__all__ = ["BeamSearchDecoder", "dynamic_decode", "sample_token",
           "fused_sample_token", "fused_verify_tokens",
           "speculative_verify_tokens", "masked_carry_advance"]


# ---------------------------------------------------------------------------
# Shared autoregressive sampler (jit-safe, pure JAX)
# ---------------------------------------------------------------------------

def sample_token(last, temperature: float = 0.0, top_k=None, key=None):
    """ONE sampling semantics for every decode path: greedy argmax at
    ``temperature == 0``, temperature/top-k categorical otherwise.

    ``last``: [B, V] final-position logits; returns ``(tokens [B]
    int32, new_key)``. The jitted whole-generate scan, the chunked
    per-block generate, the continuous-batching engine's prefill and
    decode steps, and the speculative verify step all call THIS
    function, so their token streams provably share one sampler
    (previously the same four lines lived in three places).
    ``temperature``/``top_k`` must be Python statics under jit; ``key``
    is unused (and may be None) on the greedy path."""
    import jax

    if temperature == 0.0:
        return jnp.argmax(last, -1).astype(jnp.int32), key
    scaled = last.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -1e10, scaled)
    key, sub = jax.random.split(key)
    return jax.random.categorical(sub, scaled, axis=-1).astype(
        jnp.int32), key


def masked_carry_advance(nxt, cur, active, emitted, rem, eos):
    """Carry-form sampler update for the device-resident multi-step
    decode loop (r19, models/gpt.py ``multi_step_decode``): fold one
    freshly sampled token batch into the ``(cur, active, emitted)``
    loop carry under the per-slot active mask.

    ``nxt``: [B] int32 tokens this iteration's :func:`sample_token` /
    :func:`fused_sample_token` produced; ``cur``: [B] the previous
    carry tokens; ``active``: [B] bool, which slots are still
    generating; ``emitted``: [B] int32, tokens emitted so far THIS
    macro launch; ``rem``: [B] int32, each slot's remaining emission
    budget (``max_new_tokens - len(generated)`` at the boundary);
    ``eos``: [B] int32 EOS ids (−1 = none — token ids are >= 0, so −1
    never matches).

    Returns ``(cur', active', emitted')``. The stop rule mirrors the
    host engine's ``_finish_due`` exactly — a slot stops after
    emitting EOS or its budget's last token — so an N-step launch's
    per-slot token streams are bit-identical to N host-driven steps.
    A stopped slot keeps its last token in ``cur`` and rides the rest
    of the launch masked (the harness redirects its KV writes to the
    scratch page), exactly like a parked slot in the per-token
    engine."""
    emitted = emitted + active.astype(jnp.int32)
    stop = (nxt == eos) | (emitted >= rem)
    new_active = jnp.logical_and(active, jnp.logical_not(stop))
    return jnp.where(active, nxt, cur), new_active, emitted


def _head_logits(hidden, weight, bias, transpose_y: bool):
    """The unfused lm_head matmul (models/gpt.py ``logits`` semantics:
    ``hidden @ W.T`` for the tied [V, D] layout, ``hidden @ W`` for the
    untied [D, V] head) — the fallback the fused sampler delegates to
    whenever streaming cannot reproduce the exact unfused behavior."""
    logits = jnp.matmul(hidden, weight.T if transpose_y else weight)
    if bias is not None:
        logits = logits + bias
    return logits


def fused_sample_token(hidden, weight, temperature: float = 0.0,
                       top_k=None, key=None, transpose_y: bool = False,
                       bias=None, tile: int = 2048):
    """:func:`sample_token` twin over FINAL HIDDEN STATES + the lm_head
    weight instead of materialized logits (the r13 fused decode hot
    path): the jitted whole-generate scan, the continuous-batching
    engine's fused prefill/decode steps and the fused speculative
    verify all call THIS function, so their token streams still share
    ONE sampler while the [B, vocab] logits tensor never reaches HBM
    on the paths that can stream it.

    ``hidden``: [B, D]; ``weight``/``transpose_y``/``bias``: the head
    layout (models/gpt.py ``head_params``). Routing:

    - greedy (``temperature == 0``): streaming argmax over vocab tiles
      (ops/pallas/fused_sample.py) — bit-identical tokens to
      ``argmax(logits)`` by the first-index tie rule;
    - ``top_k`` sampling: streaming top-k reservoir, then one
      categorical over the k candidates (the same top-k distribution;
      the [B, V] tensor still never materializes);
    - plain temperature sampling, or an active serving-mesh trace
      (vocab-sharded weights — GSPMD already keeps per-device logits
      tiles, and the tile scan would fight the sharding): the exact
      unfused logits + :func:`sample_token`.

    Returns ``(tokens [B] int32, new_key)`` like ``sample_token``."""
    import jax

    from ..ops.pallas.fused_sample import fused_sample
    from ..ops.pallas.paged_attention import get_head_sharding

    if get_head_sharding() is not None:
        return sample_token(_head_logits(hidden, weight, bias,
                                         transpose_y),
                            temperature, top_k, key)
    if temperature == 0.0:
        return fused_sample(hidden, weight, bias=bias,
                            transpose_y=transpose_y, tile=tile), key
    if top_k is not None:
        vals, idxs = fused_sample(hidden, weight, bias=bias,
                                  transpose_y=transpose_y, top_k=top_k,
                                  tile=tile)
        key, sub = jax.random.split(key)
        pick = jax.random.categorical(
            sub, vals.astype(jnp.float32) / temperature, axis=-1)
        tok = jnp.take_along_axis(idxs, pick[:, None], axis=1)[:, 0]
        return tok.astype(jnp.int32), key
    return sample_token(_head_logits(hidden, weight, bias, transpose_y),
                        temperature, top_k, key)


def fused_verify_tokens(hidden, drafts, weight, temperature: float = 0.0,
                        top_k=None, key=None, transpose_y: bool = False,
                        bias=None, tile: int = 2048):
    """:func:`speculative_verify_tokens` twin over the verify chunk's
    final hidden states [B, s, D]: on the greedy single-device path the
    per-position target tokens come from the STREAMING argmax (one
    fused scoring+acceptance program, no [B, s, V] logits in HBM);
    temperature/top-k verification needs full per-position
    distributions (acceptance probabilities + residual resampling), so
    those — and serving-mesh traces — delegate to the exact unfused
    logits + ``speculative_verify_tokens``. Same return contract."""
    from ..ops.pallas.fused_sample import fused_sample
    from ..ops.pallas.paged_attention import get_head_sharding

    b, s, d = hidden.shape
    if temperature == 0.0 and get_head_sharding() is None:
        full = fused_sample(hidden.reshape(b * s, d), weight, bias=bias,
                            transpose_y=transpose_y, tile=tile)
        full = full.reshape(b, s).astype(jnp.int32)
        accept = drafts.astype(jnp.int32) == full[:, :-1]
        return accept, full[:, :-1], full, key
    return speculative_verify_tokens(
        _head_logits(hidden, weight, bias, transpose_y), drafts,
        temperature, top_k, key)


def speculative_verify_tokens(logits, drafts, temperature: float = 0.0,
                              top_k=None, key=None):
    """Per-position accept/replace decisions for speculative decoding.

    ``logits``: [B, s, V] target-model logits over the verify chunk
    ``[cur, d_0, .., d_{s-2}]`` — position ``j`` scores the token that
    follows ``cur, d_0..d_{j-1}``. ``drafts``: [B, s-1] the draft
    tokens ``d_0..d_{s-2}``. Returns ``(accept [B, s-1] bool,
    resampled [B, s-1] int32, full [B, s] int32, key)``:

    - ``full[:, j]``: the token the target itself would emit at
      position ``j`` (``sample_token`` semantics — argmax when greedy),
      i.e. exactly the vanilla decode token given that prefix;
    - ``accept[:, j]``: whether draft ``d_j`` survives at position
      ``j`` — greedy: exact match against ``full``; temperature: a
      uniform draw under the target probability of ``d_j`` (the
      deterministic-draft acceptance rule, q = point mass);
    - ``resampled[:, j]``: the replacement token if ``j`` is the FIRST
      rejection — greedy: the argmax correction (== ``full``);
      temperature: a sample from the residual distribution (target
      probabilities with the rejected draft token's mass removed and
      renormalized), which keeps the emitted stream distributed
      exactly as the target model.

    The caller takes ``n`` = length of the leading all-accepted prefix
    (over its per-sequence valid draft count) and emits
    ``drafts[:n] + (resampled[n] if n < valid else full[valid])``."""
    import jax

    b, s, _ = logits.shape
    if temperature == 0.0:
        full = jnp.argmax(logits, -1).astype(jnp.int32)
        accept = drafts.astype(jnp.int32) == full[:, :-1]
        return accept, full[:, :-1], full, key
    scaled = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < kth, -1e10, scaled)
    probs = jax.nn.softmax(scaled, axis=-1)
    key, k_acc, k_resid, k_full = jax.random.split(key, 4)
    # full-distribution samples at every position (sample_token
    # semantics, batched over positions)
    full = jax.random.categorical(
        k_full, scaled.reshape(b * s, -1), axis=-1).reshape(
        b, s).astype(jnp.int32)
    d32 = drafts.astype(jnp.int32)
    p_draft = jnp.take_along_axis(probs[:, :-1], d32[..., None],
                                  axis=-1)[..., 0]
    u = jax.random.uniform(k_acc, (b, s - 1))
    accept = u < p_draft
    # residual: remove the rejected draft's mass, renormalize (in the
    # log domain: mask the draft token out and re-sample)
    masked = scaled[:, :-1].at[
        jnp.arange(b)[:, None], jnp.arange(s - 1)[None], d32].set(-1e10)
    resampled = jax.random.categorical(
        k_resid, masked.reshape(b * (s - 1), -1), axis=-1).reshape(
        b, s - 1).astype(jnp.int32)
    return accept, resampled, full, key


class BeamSearchDecoder:
    """Beam-search decoder over a recurrent cell (reference:
    fluid/layers/rnn.py BeamSearchDecoder).

    cell: an RNN cell ``(inputs, states) -> (output, new_states)``.
    output_fn: maps cell output -> logits over the vocabulary (e.g. the
    projection layer); defaults to identity.
    embedding_fn: maps token ids -> cell inputs; required unless the cell
    consumes raw ids.
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers --------------------------------------------------------------

    def _merge(self, t):
        v = t.value if isinstance(t, Tensor) else jnp.asarray(t)
        return v.reshape((-1,) + v.shape[2:])  # [B, beam, ...] -> [B*beam]

    def _split(self, v, batch):
        v = v.value if isinstance(v, Tensor) else jnp.asarray(v)
        return v.reshape((batch, self.beam_size) + v.shape[1:])

    def _logits(self, cell_out):
        out = self.output_fn(cell_out) if self.output_fn else cell_out
        return out.value if isinstance(out, Tensor) else jnp.asarray(out)

    def decode(self, initial_states, max_step_num: int):
        """Run the full beam search; returns (ids [B, T], scores [B])."""
        import jax
        # infer batch from the states pytree
        leaves = jax.tree_util.tree_leaves(
            initial_states, is_leaf=lambda t: isinstance(t, Tensor))
        batch = (leaves[0].shape[0] if leaves else 1)

        def tile_state(t):
            v = t.value if isinstance(t, Tensor) else jnp.asarray(t)
            v = jnp.repeat(v[:, None], self.beam_size, axis=1)
            return Tensor(v.reshape((-1,) + v.shape[2:]))

        states = jax.tree_util.tree_map(
            tile_state, initial_states,
            is_leaf=lambda t: isinstance(t, Tensor))

        tokens = jnp.full((batch, self.beam_size), self.start_token,
                          jnp.int32)
        # first expansion starts from one live beam per batch row
        scores = jnp.where(
            jnp.arange(self.beam_size)[None, :] == 0, 0.0, -jnp.inf
        ) * jnp.ones((batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        ids_steps, parent_steps = [], []

        for _ in range(max_step_num):
            flat_tok = Tensor(tokens.reshape(-1))
            inp = self.embedding_fn(flat_tok) if self.embedding_fn \
                else flat_tok
            cell_out, states = self.cell(inp, states)
            logits = self._logits(cell_out)            # [B*beam, V]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(batch, self.beam_size, -1)
            scores, parent, tok = beam_search_step(
                logp, scores, self.beam_size, end_token=self.end_token,
                finished=finished)
            # reorder states along the chosen parents
            flat_parent = (parent +
                           jnp.arange(batch)[:, None] * self.beam_size
                           ).reshape(-1)
            states = jax.tree_util.tree_map(
                lambda t: Tensor(jnp.take(
                    t.value if isinstance(t, Tensor) else jnp.asarray(t),
                    flat_parent, axis=0)),
                states, is_leaf=lambda t: isinstance(t, Tensor))
            finished = jnp.take_along_axis(finished, parent, axis=1) | (
                tok == self.end_token)
            tokens = tok
            ids_steps.append(tok)
            parent_steps.append(parent)
            from jax._src import core as _jc
            if _jc.trace_state_clean() and bool(jnp.all(finished)):
                break  # eager early exit; under jit the loop is static

        ids = jnp.stack(ids_steps)                     # [T, B, beam]
        parents = jnp.stack(parent_steps)
        full = gather_tree(ids, parents)               # [T, B, beam]
        best = jnp.argmax(scores, axis=1)              # [B]
        seq = jnp.take_along_axis(
            full, best[None, :, None], axis=2)[:, :, 0]
        return Tensor(seq.swapaxes(0, 1)), Tensor(
            jnp.max(scores, axis=1))

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """reference helper: repeat batch entries beam_size times."""
        v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]))


def dynamic_decode(decoder, inits=None, max_step_num: int = 100,
                   output_time_major: bool = False, impute_finished=False,
                   is_test: bool = False, return_length: bool = False,
                   **kwargs):
    """Drive a decoder to completion (reference: fluid/layers/rnn.py
    dynamic_decode). Returns (ids, scores) — and lengths when
    ``return_length``."""
    ids, scores = decoder.decode(inits, max_step_num)
    lengths = None
    if return_length:
        v = ids.value  # [B, T] batch-major here, before any transpose
        lengths = jnp.argmax(
            jnp.concatenate(
                [(v == decoder.end_token),
                 jnp.ones_like(v[:, :1], bool)], axis=1), axis=1)
    if output_time_major:
        ids = F["transpose"](ids, [1, 0])
    if return_length:
        return ids, scores, Tensor(lengths.astype(jnp.int32))
    return ids, scores
