"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference parity: python/paddle/fluid/layers/rnn.py BeamSearchDecoder /
dynamic_decode (exported as paddle.nn.BeamSearchDecoder,
paddle.nn.dynamic_decode).

TPU-native design: the reference drives a While loop of beam_search +
beam_search_decode ops over LoD tensors; here decoding is a dense
fixed-shape loop over ``ops.decode_extra.beam_search_step`` (top-k over
MXU-friendly [batch*beam, vocab] logits) with the backtrace done by
``gather_tree`` — the whole decode can sit inside one jit when shapes are
static.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from .. import dispatch
from ..ops.decode_extra import beam_search_step, gather_tree
from ..tensor import Tensor
from .layer import Layer

F = dispatch.wrapped_ops

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Beam-search decoder over a recurrent cell (reference:
    fluid/layers/rnn.py BeamSearchDecoder).

    cell: an RNN cell ``(inputs, states) -> (output, new_states)``.
    output_fn: maps cell output -> logits over the vocabulary (e.g. the
    projection layer); defaults to identity.
    embedding_fn: maps token ids -> cell inputs; required unless the cell
    consumes raw ids.
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers --------------------------------------------------------------

    def _merge(self, t):
        v = t.value if isinstance(t, Tensor) else jnp.asarray(t)
        return v.reshape((-1,) + v.shape[2:])  # [B, beam, ...] -> [B*beam]

    def _split(self, v, batch):
        v = v.value if isinstance(v, Tensor) else jnp.asarray(v)
        return v.reshape((batch, self.beam_size) + v.shape[1:])

    def _logits(self, cell_out):
        out = self.output_fn(cell_out) if self.output_fn else cell_out
        return out.value if isinstance(out, Tensor) else jnp.asarray(out)

    def decode(self, initial_states, max_step_num: int):
        """Run the full beam search; returns (ids [B, T], scores [B])."""
        import jax
        # infer batch from the states pytree
        leaves = jax.tree_util.tree_leaves(
            initial_states, is_leaf=lambda t: isinstance(t, Tensor))
        batch = (leaves[0].shape[0] if leaves else 1)

        def tile_state(t):
            v = t.value if isinstance(t, Tensor) else jnp.asarray(t)
            v = jnp.repeat(v[:, None], self.beam_size, axis=1)
            return Tensor(v.reshape((-1,) + v.shape[2:]))

        states = jax.tree_util.tree_map(
            tile_state, initial_states,
            is_leaf=lambda t: isinstance(t, Tensor))

        tokens = jnp.full((batch, self.beam_size), self.start_token,
                          jnp.int32)
        # first expansion starts from one live beam per batch row
        scores = jnp.where(
            jnp.arange(self.beam_size)[None, :] == 0, 0.0, -jnp.inf
        ) * jnp.ones((batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        ids_steps, parent_steps = [], []

        for _ in range(max_step_num):
            flat_tok = Tensor(tokens.reshape(-1))
            inp = self.embedding_fn(flat_tok) if self.embedding_fn \
                else flat_tok
            cell_out, states = self.cell(inp, states)
            logits = self._logits(cell_out)            # [B*beam, V]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(batch, self.beam_size, -1)
            scores, parent, tok = beam_search_step(
                logp, scores, self.beam_size, end_token=self.end_token,
                finished=finished)
            # reorder states along the chosen parents
            flat_parent = (parent +
                           jnp.arange(batch)[:, None] * self.beam_size
                           ).reshape(-1)
            states = jax.tree_util.tree_map(
                lambda t: Tensor(jnp.take(
                    t.value if isinstance(t, Tensor) else jnp.asarray(t),
                    flat_parent, axis=0)),
                states, is_leaf=lambda t: isinstance(t, Tensor))
            finished = jnp.take_along_axis(finished, parent, axis=1) | (
                tok == self.end_token)
            tokens = tok
            ids_steps.append(tok)
            parent_steps.append(parent)
            from jax._src import core as _jc
            if _jc.trace_state_clean() and bool(jnp.all(finished)):
                break  # eager early exit; under jit the loop is static

        ids = jnp.stack(ids_steps)                     # [T, B, beam]
        parents = jnp.stack(parent_steps)
        full = gather_tree(ids, parents)               # [T, B, beam]
        best = jnp.argmax(scores, axis=1)              # [B]
        seq = jnp.take_along_axis(
            full, best[None, :, None], axis=2)[:, :, 0]
        return Tensor(seq.swapaxes(0, 1)), Tensor(
            jnp.max(scores, axis=1))

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """reference helper: repeat batch entries beam_size times."""
        v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        v = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(v.reshape((-1,) + v.shape[2:]))


def dynamic_decode(decoder, inits=None, max_step_num: int = 100,
                   output_time_major: bool = False, impute_finished=False,
                   is_test: bool = False, return_length: bool = False,
                   **kwargs):
    """Drive a decoder to completion (reference: fluid/layers/rnn.py
    dynamic_decode). Returns (ids, scores) — and lengths when
    ``return_length``."""
    ids, scores = decoder.decode(inits, max_step_num)
    lengths = None
    if return_length:
        v = ids.value  # [B, T] batch-major here, before any transpose
        lengths = jnp.argmax(
            jnp.concatenate(
                [(v == decoder.end_token),
                 jnp.ones_like(v[:, :1], bool)], axis=1), axis=1)
    if output_time_major:
        ids = F["transpose"](ids, [1, 0])
    if return_length:
        return ids, scores, Tensor(lengths.astype(jnp.int32))
    return ids, scores
