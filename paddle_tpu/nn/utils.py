"""Parametrization hooks: weight_norm / spectral_norm.

Reference parity: python/paddle/nn/utils/weight_norm_hook.py
(weight_norm/remove_weight_norm) and spectral_norm_hook.py — implemented
as forward pre-hooks that recompute the derived weight from the
reparametrized parameters, so optimizers see only (g, v) / the raw
orig weight, and the derived value participates in autograd through
the eager tape / jit trace.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import dispatch
from ..tensor import Parameter, Tensor
from .layer import Layer

F = dispatch.wrapped_ops


def _norm_except_dim(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return F["sqrt"](F["sum"](v * v, axis=axes, keepdim=True))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    """Reparametrize ``layer.<name>`` as g * v / ||v|| (per-slice norms
    along ``dim``). Returns the layer."""
    w = layer._parameters[name]
    dim = dim % w.ndim if dim is not None else None
    if dim is None:
        g0 = F["sqrt"](F["sum"](Tensor(w.value) * Tensor(w.value)))
        g0 = g0.value.reshape(())
    else:
        g0 = _norm_except_dim(Tensor(w.value), dim).value
    del layer._parameters[name]
    layer.__setattr__(name + "_g", Parameter(g0))
    layer.__setattr__(name + "_v", Parameter(w.value))

    def _compute(lyr, _inputs):
        g = lyr._parameters[name + "_g"]
        v = lyr._parameters[name + "_v"]
        if dim is None:
            nrm = F["sqrt"](F["sum"](v * v))
        else:
            nrm = _norm_except_dim(v, dim)
        object.__setattr__(lyr, name, v * (g / nrm))
        return None

    helper = layer.register_forward_pre_hook(_compute)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (helper, dim)
    _compute(layer, None)  # materialize once for direct .weight access
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    helper, dim = layer._weight_norm_hooks.pop(name)
    helper.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    if dim is None:
        nrm = F["sqrt"](F["sum"](Tensor(v.value) * Tensor(v.value)))
    else:
        nrm = _norm_except_dim(Tensor(v.value), dim)
    w = (Tensor(v.value) * (Tensor(g.value) / nrm)).value
    layer.__dict__.pop(name, None)
    layer.__setattr__(name, Parameter(w))
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations:
                  int = 1, eps: float = 1e-12, dim: int = 0):
    """Divide ``layer.<name>`` by its largest singular value, estimated by
    power iteration on persistent (u, v) buffers (reference
    spectral_norm_hook.py / fluid SpectralNorm layer)."""
    w = layer._parameters[name]
    dim = dim % w.ndim
    mat = jnp.moveaxis(w.value, dim, 0).reshape(w.shape[dim], -1)
    h, ww = mat.shape
    import numpy as np
    rng = np.random.default_rng(0)
    layer.register_buffer(name + "_u", Tensor(
        _l2norm(jnp.asarray(rng.standard_normal(h), mat.dtype), eps)))
    layer.register_buffer(name + "_v", Tensor(
        _l2norm(jnp.asarray(rng.standard_normal(ww), mat.dtype), eps)))
    orig = Parameter(w.value)
    del layer._parameters[name]
    layer.__setattr__(name + "_orig", orig)

    def _compute(lyr, _inputs):
        wo = lyr._parameters[name + "_orig"]
        u = lyr._buffers[name + "_u"].value
        v = lyr._buffers[name + "_v"].value
        m_raw = jnp.moveaxis(wo.value, dim, 0).reshape(wo.shape[dim], -1)
        for _ in range(max(1, n_power_iterations)):
            v = _l2norm(m_raw.T @ u, eps)
            u = _l2norm(m_raw @ v, eps)
        lyr._buffers[name + "_u"].value = u
        lyr._buffers[name + "_v"].value = v
        # sigma through the live (possibly taped/traced) weight
        wt = wo if isinstance(wo, Tensor) else Tensor(wo)
        flat = F["reshape"](F["moveaxis"](wt, dim, 0),
                            (wo.shape[dim], -1))
        sigma = F["sum"](flat * Tensor(jnp.outer(u, v)))
        object.__setattr__(lyr, name, wt / sigma)
        return None

    layer.register_forward_pre_hook(_compute)
    _compute(layer, None)
    return layer


def _l2norm(x, eps):
    return x / (jnp.linalg.norm(x) + eps)


def parameters_to_vector(parameters):
    return F["concat"]([F["reshape"](p, (-1,)) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters):
    import numpy as np
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p.value = vec[offset:offset + n].value.reshape(p.shape) \
            if isinstance(vec, Tensor) else vec[offset:offset + n].reshape(
                p.shape)
        offset += n
