"""Layer containers (reference: python/paddle/fluid/dygraph/container.py —
Sequential, LayerList, ParameterList; layers.py LayerDict)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Iterable

from ..tensor import Parameter
from .layer import Layer


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, item in enumerate(layers):
                if isinstance(item, (list, tuple)) and len(item) == 2:
                    self.add_sublayer(item[0], item[1])
                else:
                    self.add_sublayer(str(i), item)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers: Iterable[Layer] = ()):
        super().__init__()
        for i, layer in enumerate(sublayers):
            self.add_sublayer(str(i), layer)

    def append(self, layer: Layer) -> "LayerList":
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers) -> "LayerList":
        for l in layers:
            self.append(l)
        return self

    def insert(self, index: int, layer: Layer) -> None:
        existing = list(self._sub_layers.values())
        existing.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(existing):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        n = len(self._sub_layers)
        if idx < 0:
            idx += n
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters: Iterable[Parameter] = ()):
        super().__init__()
        for i, p in enumerate(parameters):
            self.add_parameter(str(i), p)

    def append(self, parameter: Parameter) -> "ParameterList":
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        n = len(self._parameters)
        if idx < 0:
            idx += n
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def update(self, sublayers) -> None:
        items = sublayers.items() if isinstance(sublayers, dict) else \
            sublayers
        for name, layer in items:
            self.add_sublayer(name, layer)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers
