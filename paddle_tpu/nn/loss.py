"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""

from __future__ import annotations

from .. import dispatch
from .layer import Layer

F = dispatch.wrapped_ops


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):  # noqa: A002
        return F["cross_entropy"](
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label,
            axis=self.axis, use_softmax=self.use_softmax,
            label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["mse_loss"](input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["l1_loss"](input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F["smooth_l1_loss"](input, label, self.reduction, self.delta)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["nll_loss"](input, label, self.weight, self.ignore_index,
                             self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["binary_cross_entropy"](input, label, self.weight,
                                         self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F["binary_cross_entropy_with_logits"](
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["kl_div"](input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F["margin_ranking_loss"](input, other, label, self.margin,
                                        self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["hinge_embedding_loss"](input, label, self.margin,
                                         self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F["cosine_embedding_loss"](input1, input2, label, self.margin,
                                          self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, anchor, positive, negative):
        return F["triplet_margin_loss"](anchor, positive, negative,
                                        self.margin, self.p, self.epsilon,
                                        self.swap, self.reduction)


class CTCLoss(Layer):
    """CTC loss layer (reference: python/paddle/nn/layer/loss.py CTCLoss
    over operators/warpctc_op.cc; native log-space scan here)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F["ctc_loss"](log_probs, labels, input_lengths,
                             label_lengths, blank=self.blank,
                             reduction=self.reduction,
                             norm_by_times=norm_by_times)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer with owned parameters
    (reference: python/paddle/nn/layer/loss.py HSigmoidLoss over
    operators/hierarchical_sigmoid_op.cc)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.is_custom = is_custom
        # default SimpleCode tree touches internal nodes 0..num_classes-2
        # (reference weight shape [num_classes-1, D]); custom trees index
        # up to num_classes rows
        n_nodes = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter((n_nodes, feature_size),
                                            attr=weight_attr)
        self.bias = (None if bias_attr is False
                     else self.create_parameter((n_nodes,), is_bias=True,
                                                attr=bias_attr))

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F["hsigmoid_loss"](
            input, label, self.weight, self.bias,
            num_classes=self.num_classes, path_table=path_table,
            path_code=path_code)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["soft_margin_loss"](input, label,
                                     reduction=self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F["multi_label_soft_margin_loss"](
            input, label, weight=self.weight, reduction=self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):  # noqa: A002
        li, fu, ep, red = self._args
        return F["poisson_nll_loss"](input, label, log_input=li, full=fu,
                                     epsilon=ep, reduction=red)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):  # noqa: A002
        fu, ep, red = self._args
        return F["gaussian_nll_loss"](input, label, variance, full=fu,
                                      epsilon=ep, reduction=red)
