"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""

from __future__ import annotations

import numpy as np

from .. import dispatch
from .initializer import KaimingUniform, Uniform
from .layer import Layer

F = dispatch.wrapped_ops


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, bias_attr, weight_attr,
                 data_format, ndim, transposed=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        k = kernel_size if isinstance(kernel_size, (list, tuple)) else \
            (kernel_size,) * ndim
        self._kernel_size = tuple(k)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._output_padding = output_padding
        if transposed:
            w_shape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self._kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            bound = 1.0 / np.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), is_bias=True, attr=bias_attr,
                default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         data_format, 1)

    def forward(self, x):
        return F["conv1d"](x, self.weight, self.bias, self._stride,
                           self._padding, self._dilation, self._groups,
                           self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         data_format, 2)

    def forward(self, x):
        return F["conv2d"](x, self.weight, self.bias, self._stride,
                           self._padding, self._dilation, self._groups,
                           self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         data_format, 3)

    def forward(self, x):
        return F["conv3d"](x, self.weight, self.bias, self._stride,
                           self._padding, self._dilation, self._groups,
                           self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         data_format, 1, transposed=True,
                         output_padding=output_padding)

    def forward(self, x):
        return F["conv1d_transpose"](x, self.weight, self.bias, self._stride,
                                     self._padding, self._output_padding,
                                     self._dilation, self._groups,
                                     self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         data_format, 2, transposed=True,
                         output_padding=output_padding)

    def forward(self, x):
        return F["conv2d_transpose"](x, self.weight, self.bias, self._stride,
                                     self._padding, self._output_padding,
                                     self._dilation, self._groups,
                                     self._data_format)


class Conv3DTranspose(_ConvNd):
    """3D transposed conv layer (reference: python/paddle/nn/layer/conv.py
    Conv3DTranspose over operators/conv_transpose_op.cc)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         data_format, 3, transposed=True,
                         output_padding=output_padding)

    def forward(self, x):
        return F["conv3d_transpose"](x, self.weight, self.bias, self._stride,
                                     self._padding, self._output_padding,
                                     self._dilation, self._groups,
                                     self._data_format)


class DeformConv2D(_ConvNd):
    """Deformable conv v1/v2 layer (reference:
    python/paddle/vision/ops.py DeformConv2D over
    operators/deformable_conv_op.cc); pass `mask` for modulated (v2)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, bias_attr, weight_attr,
                         "NCHW", 2)
        self._deformable_groups = deformable_groups

    def forward(self, x, offset, mask=None):
        return F["deformable_conv"](x, offset, self.weight, mask, self.bias,
                                    self._stride, self._padding,
                                    self._dilation, self._deformable_groups,
                                    self._groups)
