"""Layer: the eager module system.

TPU-native equivalent of the reference's dygraph Layer
(reference: python/paddle/fluid/dygraph/layers.py:81 Layer — parameters,
sublayers, buffers, forward pre/post hooks, state_dict/set_state_dict,
train/eval, apply). Plus the TPU-specific extra: ``functional_state`` /
``bind_state`` lift a stateful Layer into a pure function over a params
pytree so the same eager-defined model runs under jit/pjit/grad — the
equivalent of how the reference shares one kernel registry between dygraph
and static modes.
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.enforce import InvalidArgumentError
from ..tensor import Parameter, Tensor
from .initializer import Initializer, get_initializer


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks: Dict[int, Callable]):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self) -> None:
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self._dtype = convert_dtype(dtype)
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names: set = set()
        self._forward_pre_hooks: Dict[int, Callable] = OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = OrderedDict()
        self.training = True
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- construction helpers -------------------------------------------------

    def create_parameter(self, shape, dtype=None, default_initializer=None,
                         is_bias: bool = False, attr=None) -> Parameter:
        from .initializer import resolve_initializer
        dtype = convert_dtype(dtype or self._dtype)
        init = resolve_initializer(default_initializer, attr, is_bias)
        value = init(tuple(shape), dtype)
        name = getattr(attr, "name", None) if attr is not None else None
        p = Parameter(value, name=name)
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.trainable = False
            p.stop_gradient = True
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute plumbing ---------------------------------------------------

    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise InvalidArgumentError(
                    "call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise InvalidArgumentError(
                    "call Layer.__init__ before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                raise InvalidArgumentError(
                    f"cannot overwrite parameter {name!r} with non-Parameter")
            if buffers is not None and name in buffers:
                buffers[name] = value if (value is None or isinstance(
                    value, Tensor)) else Tensor(jnp.asarray(value))
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{self.__class__.__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name: str) -> None:
        if name in self._parameters:
            del self._parameters[name]
        elif name in self._sub_layers:
            del self._sub_layers[name]
        elif name in self._buffers:
            del self._buffers[name]
            self._non_persistable_buffer_names.discard(name)
        else:
            object.__delattr__(self, name)

    # -- call + hooks ---------------------------------------------------------

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{self.__class__.__name__} must implement forward()")

    # -- traversal ------------------------------------------------------------

    def named_parameters(self, prefix: str = "", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else
                       f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for item in layer.named_parameters(sub_prefix):
                    yield item

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "") -> Iterator[Tuple[str, Tensor]]:
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        for lname, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            for item in layer.named_buffers(sub_prefix):
                yield item

    def buffers(self) -> List[Tensor]:
        return [b for _, b in self.named_buffers()]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            for item in layer.named_sublayers(sub_prefix):
                yield item

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self._sub_layers.items():
            if l is not None:
                yield l

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- train/eval -----------------------------------------------------------

    def train(self) -> "Layer":
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self) -> "Layer":
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # -- state dict -----------------------------------------------------------

    def state_dict(self, include_sublayers=True, structured_name_prefix="",
                   include_non_persistable_buffer=False
                   ) -> "OrderedDict[str, Tensor]":
        out: "OrderedDict[str, Tensor]" = OrderedDict()
        for name, p in self.named_parameters():
            out[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            leaf = name.rsplit(".", 1)[-1]
            owner = self._locate_owner(name)
            if (not include_non_persistable_buffer and owner is not None and
                    leaf in owner._non_persistable_buffer_names):
                continue
            out[structured_name_prefix + name] = b
        return out

    def _locate_owner(self, dotted: str) -> Optional["Layer"]:
        parts = dotted.split(".")[:-1]
        layer: Layer = self
        for p in parts:
            nxt = layer._sub_layers.get(p)
            if nxt is None:
                return None
            layer = nxt
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True) -> None:
        own = self.state_dict(include_non_persistable_buffer=True)
        missing = []
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            value = state_dict[name]
            arr = value.value if isinstance(value, Tensor) else jnp.asarray(
                np.asarray(value))
            if tuple(arr.shape) != tuple(target.shape):
                raise InvalidArgumentError(
                    f"shape mismatch for {name}: {tuple(arr.shape)} vs "
                    f"{tuple(target.shape)}")
            target.value = arr.astype(target.dtype)
        return missing

    load_dict = set_state_dict

    # -- dtype/device movement ------------------------------------------------

    def to(self, device=None, dtype=None) -> "Layer":
        if dtype is not None:
            dtype = convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p.value = p.value.astype(dtype)
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                    b.value = b.value.astype(dtype)
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- misc -----------------------------------------------------------------

    def clear_gradients(self) -> None:
        for p in self.parameters():
            p.clear_grad()

    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self) -> str:
        lines = []
        extra = self.extra_repr()
        for name, layer in self._sub_layers.items():
            body = repr(layer).split("\n")
            head = f"({name}): {body[0]}"
            lines.append("  " + head)
            lines.extend("  " + b for b in body[1:])
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


# -- functional capture -------------------------------------------------------

def functional_state(layer: Layer, trainable_only: bool = False
                     ) -> Dict[str, Any]:
    """Extract raw-array state: {'params': {...}, 'buffers': {...}}."""
    params = {n: p.value for n, p in layer.named_parameters()
              if p is not None and (not trainable_only or p.trainable)}
    buffers = {n: b.value for n, b in layer.named_buffers() if b is not None}
    return {"params": params, "buffers": buffers}


def functional_state_shardings(layer: Layer, mesh) -> Dict[str, Any]:
    """NamedSharding tree matching :func:`functional_state`'s structure,
    from each Parameter/buffer's ``.pspec`` annotation (mp_layers.py
    sets these) projected onto ``mesh`` via ``filter_pspec`` —
    unannotated leaves replicate. The decode engine feeds this to
    ``jax.device_put`` so GSPMD serves the model tensor-parallel with
    the exact layout the fleet side trains it in."""
    from jax.sharding import NamedSharding

    from ..distributed.topology import filter_pspec

    def sh(obj):
        return NamedSharding(mesh,
                             filter_pspec(getattr(obj, "pspec", None),
                                          mesh))

    params = {n: sh(p) for n, p in layer.named_parameters()
              if p is not None}
    buffers = {n: sh(b) for n, b in layer.named_buffers()
               if b is not None}
    return {"params": params, "buffers": buffers}


@contextlib.contextmanager
def bind_state(layer: Layer, state: Dict[str, Any]):
    """Temporarily substitute raw values (possibly tracers) into the layer's
    Parameters/buffers; restore on exit. The layer's forward then computes
    on the substituted values, making it a pure function of ``state``."""
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    saved_p = {n: p.value for n, p in named_p.items()}
    saved_b = {n: b.value for n, b in named_b.items()}
    try:
        for n, v in state.get("params", {}).items():
            if n in named_p:
                named_p[n].value = v
        for n, v in state.get("buffers", {}).items():
            if n in named_b:
                named_b[n].value = v
        yield layer
    finally:
        for n, p in named_p.items():
            p.value = saved_p[n]
        for n, b in named_b.items():
            b.value = saved_b[n]


def functional_call(layer: Layer, state: Dict[str, Any], *args,
                    training: Optional[bool] = None, rng_key=None,
                    mutable_buffers: bool = False, **kwargs):
    """Run layer.forward as a pure function of (state, *args).

    Returns output raw arrays, or (output, new_buffers) if
    ``mutable_buffers`` (for BatchNorm-style running stats under jit).
    """
    from ..autograd.engine import no_grad
    from ..core import rng as rng_mod

    prev_training = layer.training
    if training is not None:
        (layer.train() if training else layer.eval())
    try:
        with bind_state(layer, state), no_grad():
            with rng_mod.key_scope(rng_key) if rng_key is not None else \
                    contextlib.nullcontext():
                out = layer(*args, **kwargs)
            out_raw = jax.tree_util.tree_map(
                lambda t: t.value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
            if mutable_buffers:
                new_buffers = {n: b.value for n, b in layer.named_buffers()
                               if b is not None}
                return out_raw, new_buffers
            return out_raw
    finally:
        if training is not None:
            (layer.train() if prev_training else layer.eval())
