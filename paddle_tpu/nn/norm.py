"""Normalization layers (reference: python/paddle/nn/layer/norm.py —
BatchNorm1D/2D/3D, LayerNorm, GroupNorm, InstanceNorm, SyncBatchNorm,
SpectralNorm; plus RMSNorm which the TPU build adds for LLMs)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import dispatch
from ..tensor import Tensor
from .layer import Layer

F = dispatch.wrapped_ops


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=lambda s, d: jnp.ones(s, d))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_features,), is_bias=True,
                                              attr=bias_attr)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        training = self.training and not (self._use_global_stats is True)
        out, new_m, new_v = F["batch_norm"](
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format)
        if training:
            self._mean.set_value(new_m.detach() if isinstance(
                new_m, Tensor) else new_m)
            self._variance.set_value(new_v.detach() if isinstance(
                new_v, Tensor) else new_v)
        return out

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """Fluid-era BatchNorm signature (reference: fluid/dygraph/nn.py
    BatchNorm(num_channels, act, is_test, momentum, epsilon, param_attr,
    bias_attr, dtype, data_layout, ...)); the 2.0-style BatchNorm1D/2D/3D
    subclasses keep the modern signature."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr,
                         data_format=data_layout,
                         use_global_stats=use_global_stats or None)
        self._act = act
        if is_test:
            self.eval()

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from .. import dispatch
            out = dispatch.apply(self._act, out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/shard_map the batch axis is sharded and
    XLA computes global statistics automatically when the reduction spans the
    mesh; for eager DDP use, stats sync happens via the collective API
    (reference: nn/layer/norm.py SyncBatchNorm over c_sync_* ops)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer: Layer) -> Layer:
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=lambda s, d: jnp.ones(s, d))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              is_bias=True, attr=bias_attr)

    def forward(self, x):
        return F["layer_norm"](x, self._normalized_shape, self.weight,
                               self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """Root-mean-square norm (beyond-reference: standard for LLM blocks)."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=lambda s, d: jnp.ones(s, d))

    def forward(self, x):
        return F["rms_norm"](x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=lambda s, d: jnp.ones(s, d))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_channels,), is_bias=True,
                                              attr=bias_attr)

    def forward(self, x):
        return F["group_norm"](x, self._num_groups, self.weight, self.bias,
                               self._epsilon, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight, self.bias = None, None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=lambda s, d: jnp.ones(s, d))
            self.bias = self.create_parameter((num_features,), is_bias=True,
                                              attr=bias_attr)

    def forward(self, x):
        return F["instance_norm"](x, self.weight, self.bias, self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self._data_format = data_format

    def forward(self, x):
        return F["local_response_norm"](x, self.size, self.alpha, self.beta,
                                        self.k, self._data_format)


class DataNorm(Layer):
    """CTR data normalization with accumulated statistics (reference:
    fluid layers.data_norm / operators/data_norm_op.cc). Buffers
    batch_size/batch_sum/batch_square_sum accumulate during training;
    forward normalizes from the accumulated moments."""

    def __init__(self, num_features, epsilon=1e-4,
                 slot_dim: int = -1, summary_decay_rate: float = 0.9999999,
                 name=None):
        super().__init__()
        if slot_dim > 0:
            raise NotImplementedError(
                "DataNorm slot_dim>0 (show/click slot handling) is not "
                "implemented; pass slot_dim=-1 for plain per-feature "
                "normalization")
        self._epsilon = epsilon
        self._decay = summary_decay_rate
        init_size = 1e4
        self.register_buffer("batch_size", Tensor(
            jnp.full((num_features,), init_size, jnp.float32)))
        self.register_buffer("batch_sum", Tensor(
            jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("batch_square_sum", Tensor(
            jnp.full((num_features,), init_size, jnp.float32)))

    def forward(self, x):
        out = F["data_norm"](x, self.batch_size, self.batch_sum,
                             self.batch_square_sum, self._epsilon)
        if self.training:
            xv = x.value if isinstance(x, Tensor) else x
            n = x.shape[0]
            d = self._decay
            mean = self.batch_sum.value / self.batch_size.value
            self.batch_size.value = self.batch_size.value * d + n
            self.batch_sum.value = self.batch_sum.value * d + xv.sum(0)
            # centered accumulator (reference: square sums are taken
            # around the running mean, so scales = sqrt(size/square_sum))
            self.batch_square_sum.value = (
                self.batch_square_sum.value * d +
                ((xv - mean) ** 2).sum(0))
        return out


class SpectralNorm(Layer):
    """Spectral normalization of an input weight tensor (reference:
    paddle.nn.SpectralNorm, operators/spectral_norm_op.cc): maintains the
    power-iteration vectors u/v as buffers and returns W / sigma(W)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= int(s)
        import numpy as _np
        from ..core.rng import next_key
        import jax
        ku, kv = jax.random.split(next_key())
        u = jax.random.normal(ku, (h,), self._dtype)
        v = jax.random.normal(kv, (w,), self._dtype)
        self.register_buffer("weight_u", Tensor(
            u / (jnp.linalg.norm(u) + eps), stop_gradient=True))
        self.register_buffer("weight_v", Tensor(
            v / (jnp.linalg.norm(v) + eps), stop_gradient=True))

    def forward(self, weight):
        wv = weight.value if isinstance(weight, Tensor) else jnp.asarray(
            weight)
        wm = jnp.moveaxis(wv, self.dim, 0).reshape(wv.shape[self.dim], -1)
        u = self.weight_u.value
        v = self.weight_v.value
        for _ in range(max(1, self.power_iters)):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        from jax._src import core as _jc
        if _jc.trace_state_clean():  # persist power-iteration state eagerly
            self.weight_u.set_value(u)
            self.weight_v.set_value(v)
        sigma = u @ wm @ v
        return F["divide"](weight, Tensor(sigma))
