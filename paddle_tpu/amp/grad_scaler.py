"""Loss scaling for fp16 parity.

Reference parity: python/paddle/amp/grad_scaler.py GradScaler over
fluid/dygraph/amp/loss_scaler.py:27 AmpScaler (dynamic loss scaling with
incr/decr ratios, operators/amp/check_finite_and_unscale_op +
update_loss_scaling_op semantics). bf16 training on TPU does not need
scaling — with enable=False (or bf16 autocast) this is a transparent
pass-through, matching how the reference's scaler behaves when disabled.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float =
                 2.0 ** 15, incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._dynamic

    def get_loss_scaling(self) -> float:
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer) -> None:
        if not self._enable:
            return
        params = optimizer._parameter_list or []
        inv = 1.0 / self._scale
        found = False
        for p in params:
            if p is not None and p.grad is not None:
                g = p.grad.value * inv
                if bool(jnp.any(~jnp.isfinite(g))):
                    found = True
                p.grad.value = g
        self._found_inf = found

    def step(self, optimizer) -> None:
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss) -> None:
        scaled_loss.backward()
        self.step(optimizer)

    def update(self) -> None:
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self) -> Dict:
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state: Dict) -> None:
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._enable = state.get("enable", self._enable)
        self._dynamic = state.get("use_dynamic_loss_scaling", self._dynamic)


AmpScaler = GradScaler
