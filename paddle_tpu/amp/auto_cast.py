"""auto_cast: eager autocast context.

Reference parity: fluid/dygraph/amp/auto_cast.py:93 amp_guard +
imperative/amp_auto_cast.cc:27-55 white/black lists. The dispatch layer
consults amp_state() per op: white-list ops (MXU-bound matmul/conv) cast
floating inputs down to the amp dtype; black-list ops (numerically
sensitive) cast up to float32.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Set

import jax.numpy as jnp

from ..core.dtype import convert_dtype

# reference white list (imperative/amp_auto_cast.cc): matmul/conv-class ops
white_list: Set[str] = {
    "matmul", "mm", "bmm", "dot", "addmm", "linear", "conv1d", "conv2d",
    "conv3d", "conv1d_transpose", "conv2d_transpose", "einsum",
    "scaled_dot_product_attention", "flash_attention",
}

# reference black list: numerically-sensitive ops stay fp32
black_list: Set[str] = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "mean", "sum",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "layer_norm", "rms_norm", "batch_norm", "instance_norm", "group_norm",
    "local_response_norm", "nll_loss", "binary_cross_entropy", "kl_div",
    "binary_cross_entropy_with_logits", "mse_loss", "cosine_similarity",
    "norm", "var", "std", "logcumsumexp", "erf", "erfinv", "pow",
}


class _AmpTLS(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white: Set[str] = set()
        self.custom_black: Set[str] = set()


_tls = _AmpTLS()


def amp_state() -> Optional[_AmpTLS]:
    return _tls if _tls.enabled else None


def effective_lists():
    return (white_list | _tls.custom_white) - _tls.custom_black, \
        (black_list | _tls.custom_black) - _tls.custom_white


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1",
              dtype: str = "bfloat16"):
    """Enable autocast for the enclosed eager region
    (reference: paddle.amp.auto_cast)."""
    prev = (_tls.enabled, _tls.dtype, _tls.level, _tls.custom_white,
            _tls.custom_black)
    _tls.enabled = enable
    _tls.dtype = convert_dtype(dtype)
    _tls.level = level
    _tls.custom_white = set(custom_white_list or ())
    _tls.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_tls.enabled, _tls.dtype, _tls.level, _tls.custom_white,
         _tls.custom_black) = prev


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the amp dtype
    (reference: paddle.amp.decorate). Returns (models, optimizers)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers


def amp_target_dtype(name: str):
    """Dispatch hook: dtype this op's float inputs should be cast to under
    the active autocast scope, or None to run as-is."""
    wl, bl = effective_lists()
    if name in wl:
        return _tls.dtype
    if name in bl and _tls.level == "O1":
        return jnp.float32
    return None
