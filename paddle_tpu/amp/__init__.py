"""Automatic mixed precision — bf16-first.

Reference parity: python/paddle/amp/ (auto_cast over
fluid/dygraph/amp/auto_cast.py:93 amp_guard white/black op lists;
GradScaler over amp/loss_scaler.py:27 AmpScaler;
static fp16 transform contrib/mixed_precision/fp16_utils.py). On TPU the
low-precision dtype is bfloat16, which needs no loss scaling — GradScaler
degrades to a transparent pass-through unless fp16 is forced.
"""

from .auto_cast import (amp_state, auto_cast, black_list as AMP_BLACK_LIST,
                        decorate, white_list as AMP_WHITE_LIST)
from .grad_scaler import AmpScaler, GradScaler
