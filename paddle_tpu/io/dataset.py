"""Datasets (reference: python/paddle/fluid/dataloader/dataset.py —
Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
Subset, random_split)."""

from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __getitem__")

    def __len__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __len__")


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __iter__")

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(t) for t in tensors]
        n = arrays[0].shape[0]
        assert all(a.shape[0] == n for a in arrays), \
            "all tensors must share dim 0"
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, tuple) else (item,))
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self._cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self._cum[-1])

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self._cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else int(self._cum[ds_idx - 1])
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None
                 ) -> List[Subset]:
    total = sum(lengths)
    assert total == len(dataset), "lengths must sum to dataset size"
    rng = np.random.default_rng(generator)
    perm = rng.permutation(total)
    out, offset = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[offset:offset + n].tolist()))
        offset += n
    return out
