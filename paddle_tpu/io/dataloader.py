"""DataLoader with worker prefetch and async device transfer.

TPU-native equivalent of the reference's DataLoader stack
(reference: python/paddle/fluid/reader.py:146 DataLoader,
fluid/dataloader/dataloader_iter.py multiprocess workers + blocking queue,
operators/reader/buffered_reader.cc device prefetch). Host-side batch
assembly runs in a thread/process pool; finished numpy batches are moved to
device with jax.device_put which is asynchronous, giving the same
compute/transfer overlap the reference gets from its BufferedReader CUDA
streams.
"""

from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from typing import Callable, Optional

import jax
import numpy as np

from ..tensor import Tensor
from .collate import default_collate_fn
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler


def _to_device(batch, place=None):
    device = place.jax_device if place is not None else None

    def convert(x):
        if isinstance(x, np.ndarray):
            if x.dtype == np.float64:
                x = x.astype(np.float32)
            if x.dtype == np.int64:
                x = x.astype(np.int32)
            return Tensor(jax.device_put(x, device))
        return x

    return jax.tree_util.tree_map(convert, batch)


def _worker_initializer(counter, num_workers, dataset, worker_init_fn):
    """Pool initializer: record this worker's identity for
    io.get_worker_info(). ``counter`` is a per-DataLoader
    multiprocessing.Value, so ids are unique within one loader for both
    thread- and process-pool workers (mp.Value is inherited through
    ProcessPoolExecutor initargs; with threads it's just a locked int)."""
    from .worker_info import WorkerInfo, _set_worker_info
    with counter.get_lock():
        wid = counter.value
        counter.value += 1
    info = WorkerInfo(wid, num_workers, dataset)
    _set_worker_info(info)
    if worker_init_fn is not None:
        worker_init_fn(wid)


class _Fetcher:
    """Picklable index->batch function for pool workers. Batch assembly
    is a fault-injection site ("dataloader.fetch") and transient fetch
    errors (a flaky network filesystem, an injected worker fault) are
    retried per that site's policy; dataset bugs (TypeError/KeyError…)
    are not transient and propagate on the first call."""

    def __init__(self, dataset, collate_fn):
        self.dataset = dataset
        self.collate_fn = collate_fn

    def __call__(self, indices):
        from ..distributed.fault_inject import fault_point
        from ..distributed.resilience import get_retry_policy

        def _fetch():
            fault_point("dataloader.fetch")
            return self.collate_fn([self.dataset[i] for i in indices])

        return get_retry_policy("dataloader.fetch").call(
            _fetch, site="dataloader.fetch")


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler=None,
                 batch_size: Optional[int] = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable]
                 = None, num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: int = 0, worker_init_fn=None,
                 use_process_workers: bool = False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))
        self.use_buffer_reader = use_buffer_reader
        self.places = places
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.use_process_workers = use_process_workers
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=batch_size or 1, drop_last=drop_last)
            self.batch_size = batch_size or 1

    def __len__(self):
        if self._iterable_mode:
            raise RuntimeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # -- iteration ------------------------------------------------------------

    def _batches_sync(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                items = list(itertools.islice(it, self.batch_size))
                if not items:
                    return
                yield self.collate_fn(items)
        else:
            fetch = _Fetcher(self.dataset, self.collate_fn)
            for indices in self.batch_sampler:
                yield fetch(indices)

    def _batches_pool(self):
        fetch = _Fetcher(self.dataset, self.collate_fn)
        pool_cls = ProcessPoolExecutor if self.use_process_workers else \
            ThreadPoolExecutor
        inflight = self.num_workers * self.prefetch_factor
        import multiprocessing as mp
        init_args = {
            "initializer": _worker_initializer,
            "initargs": (mp.Value("i", 0), self.num_workers, self.dataset,
                         self.worker_init_fn),
        }
        with pool_cls(max_workers=self.num_workers, **init_args) as pool:
            pending = queue.Queue()
            it = iter(self.batch_sampler)

            def submit_next():
                try:
                    indices = next(it)
                except StopIteration:
                    return False
                pending.put(pool.submit(fetch, indices))
                return True

            alive = True
            for _ in range(inflight):
                alive = submit_next() and alive
            while not pending.empty():
                fut = pending.get()
                submit_next()
                yield fut.result()

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable_mode:
            gen = self._batches_pool()
        else:
            gen = self._batches_sync()
        place = None
        if self.places:
            place = self.places[0] if isinstance(self.places, (list, tuple))\
                else self.places
        if not self.use_buffer_reader:
            for b in gen:
                yield self._finalize(_to_device(b, place))
            return
        # device prefetch: keep a couple of device transfers in flight
        buf = []
        for b in gen:
            buf.append(_to_device(b, place))
            if len(buf) > self.prefetch_factor:
                yield self._finalize(buf.pop(0))
        for b in buf:
            yield self._finalize(b)

    def _finalize(self, batch):
        if self.return_list and isinstance(batch, dict):
            return list(batch.values())
        return batch
