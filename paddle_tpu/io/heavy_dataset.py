"""Heavy-IO datasets for PS workloads (InMemory/Queue).

Reference parity: python/paddle/fluid/dataset.py (InMemoryDataset /
QueueDataset facades) over C++ framework/data_set.cc (Dataset:43,
LoadIntoMemory:200) and data_feed.cc slot parsing. The reference streams
slot-formatted text through per-worker channels feeding DownpourWorkers;
here the same capabilities — parallel file load, local/global shuffle,
per-worker channel split, streaming queue mode — are host-side (this is
CPU data plumbing; batches then feed the normal jitted train step or the
PS trainer loop).

Slot line format (data_feed.proto MultiSlotDataFeed):
    "<slot>:<v1> <v2> ...;<slot2>:..."  — ints or floats per slot;
    a custom ``parse_fn(line) -> sample`` can replace it.
"""

from __future__ import annotations

import glob as _glob
import queue as _queue
import random
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np


def parse_slot_line(line: str) -> Dict[str, np.ndarray]:
    """Default slot parser: 'a:1 2;b:0.5' -> {'a': int64[2], 'b': f32[1]}."""
    out: Dict[str, np.ndarray] = {}
    for part in line.strip().split(";"):
        if not part:
            continue
        slot, _, vals = part.partition(":")
        toks = vals.split()
        if toks and any("." in t or "e" in t or "E" in t for t in toks):
            out[slot] = np.asarray([float(t) for t in toks], np.float32)
        else:
            out[slot] = np.asarray([int(t) for t in toks], np.int64)
    return out


def _sample_key(sample: Any) -> int:
    """Stable shard key for global shuffle (ref: shuffle-by-line-hash).
    Must be process-stable (every rank computes the same keys) and
    well-spread even for low-cardinality slots — so a real hash, never
    builtin hash() (salted per process) or raw slot values."""
    if isinstance(sample, dict):
        h = 0
        for k in sorted(sample):  # every slot: one binary slot must not
            h = zlib.crc32(np.asarray(sample[k]).tobytes(),  # collapse
                           zlib.crc32(k.encode(), h))        # the shards
        return h & 0x7FFFFFFF
    return zlib.crc32(repr(sample).encode()) & 0x7FFFFFFF


class DatasetBase:
    """Shared facade config (ref fluid/dataset.py DatasetBase)."""

    def __init__(self):
        self.filelist: List[str] = []
        self.parse_fn: Callable[[str], Any] = parse_slot_line
        self.batch_size = 1
        self.thread_num = 1
        self.drop_last = False

    def set_filelist(self, files: Sequence[str]) -> None:
        out: List[str] = []
        for f in files:
            hits = sorted(_glob.glob(f))
            out.extend(hits if hits else [f])
        self.filelist = out

    def set_parse_fn(self, fn: Callable[[str], Any]) -> None:
        self.parse_fn = fn

    def set_batch_size(self, bs: int) -> None:
        self.batch_size = int(bs)

    def set_thread(self, n: int) -> None:
        self.thread_num = max(1, int(n))

    def _batches(self, it: Iterator[Any]) -> Iterator[List[Any]]:
        buf: List[Any] = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield buf
                buf = []
        if buf and not self.drop_last:
            yield buf


class InMemoryDataset(DatasetBase):
    """Load all files into host memory; shuffle; serve per-worker
    channels (ref InMemoryDataset.load_into_memory/local_shuffle/
    global_shuffle, data_set.cc:200)."""

    def __init__(self):
        super().__init__()
        self.samples: List[Any] = []
        self._seed = 0

    # ------------------------------------------------------------ load
    def load_into_memory(self) -> None:
        if not self.filelist:
            raise ValueError("set_filelist first")

        def load_one(path: str) -> List[Any]:
            rows = []
            with open(path, "r") as f:
                for line in f:
                    if line.strip():
                        rows.append(self.parse_fn(line))
            return rows

        # executor propagates parse/IO errors to the caller — a bad line
        # must fail loudly, not silently truncate the dataset
        with ThreadPoolExecutor(max_workers=self.thread_num) as ex:
            results = list(ex.map(load_one, self.filelist))
        self.samples = [s for rows in results for s in rows]

    def release_memory(self) -> None:
        self.samples = []

    def get_memory_data_size(self) -> int:
        return len(self.samples)

    # --------------------------------------------------------- shuffle
    def set_shuffle_seed(self, seed: int) -> None:
        self._seed = int(seed)

    def local_shuffle(self) -> None:
        rng = random.Random(self._seed)
        rng.shuffle(self.samples)

    def global_shuffle(self, rank: int = 0, world_size: int = 1) -> None:
        """Deterministic hash repartition + local shuffle: every rank
        loads the SAME filelist and keeps the rows hashing to it — the
        coordination-free equivalent of the reference's shuffle through
        fleet (data_set.cc GlobalShuffle)."""
        if world_size > 1:
            self.samples = [s for s in self.samples
                            if _sample_key(s) % world_size == rank]
        self.local_shuffle()

    # ----------------------------------------------------------- serve
    def channels(self, n: Optional[int] = None) -> List[List[Any]]:
        """Split loaded samples into n worker channels (ref: per-thread
        channels feeding DeviceWorkers)."""
        n = n or self.thread_num
        return [self.samples[i::n] for i in range(n)]

    def __iter__(self) -> Iterator[List[Any]]:
        return self._batches(iter(self.samples))


class QueueDataset(DatasetBase):
    """Streaming mode: reader threads parse files into a bounded queue;
    the consumer iterates batches without materializing the dataset
    (ref QueueDataset / MultiSlotDataFeed channel pipeline)."""

    def __init__(self, capacity: int = 1024):
        super().__init__()
        self.capacity = int(capacity)

    def __iter__(self) -> Iterator[List[Any]]:
        if not self.filelist:
            raise ValueError("set_filelist first")
        q: _queue.Queue = _queue.Queue(maxsize=self.capacity)
        n_readers = min(self.thread_num, len(self.filelist))
        files = _queue.Queue()
        for p in self.filelist:
            files.put(p)
        done = threading.Semaphore(0)
        stop = threading.Event()  # set when the consumer abandons epoch
        errors: List[BaseException] = []

        def put(sample: Any) -> bool:
            while not stop.is_set():
                try:
                    q.put(sample, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def reader() -> None:
            try:
                while not stop.is_set():
                    try:
                        path = files.get_nowait()
                    except _queue.Empty:
                        return
                    with open(path, "r") as f:
                        for line in f:
                            if line.strip() and not put(
                                    self.parse_fn(line)):
                                return
            except BaseException as e:  # surface in the consumer
                errors.append(e)
            finally:
                done.release()

        for _ in range(n_readers):
            threading.Thread(target=reader, daemon=True).start()

        def drain() -> Iterator[Any]:
            finished = 0
            try:
                while True:
                    try:
                        yield q.get(timeout=0.05)
                    except _queue.Empty:
                        while done.acquire(blocking=False):
                            finished += 1
                        if errors:
                            raise errors[0]
                        if finished >= n_readers and q.empty():
                            return
            finally:
                stop.set()  # unblock readers on early consumer exit

        return self._batches(drain())
