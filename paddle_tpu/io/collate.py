"""Batch collation (reference: python/paddle/fluid/dataloader/collate.py)."""

from __future__ import annotations

import numbers
from collections.abc import Mapping, Sequence

import numpy as np


def default_collate_fn(batch):
    """Stack a list of samples into batched numpy arrays, preserving
    tuple/dict structure."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, numbers.Number):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, Mapping):
        return {k: default_collate_fn([d[k] for d in batch])
                for k in sample}
    if isinstance(sample, Sequence):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(fields)) for fields in transposed]
    # paddle Tensor / jax array leaves
    try:
        return np.stack([np.asarray(s) for s in batch], axis=0)
    except Exception:
        return batch


def default_convert_fn(batch):
    return batch
