"""paddle_tpu.io — datasets, samplers, DataLoader
(reference parity: python/paddle/io/)."""

from .collate import default_collate_fn
from .dataloader import DataLoader
from .worker_info import WorkerInfo, get_worker_info
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, WeightedRandomSampler)
