"""Samplers (reference: python/paddle/fluid/dataloader/batch_sampler.py +
sampler.py — Sampler, SequenceSampler, RandomSampler, WeightedRandomSampler,
BatchSampler, DistributedBatchSampler)."""

from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng(self.generator)
        if self.replacement:
            yield from rng.integers(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__()
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        yield from rng.choice(len(self.weights), self.num_samples,
                              replace=self.replacement, p=p).tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        assert (dataset is None) != (sampler is None), \
            "provide exactly one of dataset / sampler"
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last

    def __iter__(self):
        batch: List[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (reference:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler). On TPU the
    rank/nranks default from the distributed env (paddle_tpu.distributed)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed.env import get_rank, get_world_size
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make divisible, then take this rank's shard
        indices += indices[:(self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch: List[int] = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
