"""DataLoader worker identity (reference:
python/paddle/fluid/dataloader/worker.py get_worker_info / WorkerInfo).

Worker state is thread-local (thread-pool workers) or process-global
(process-pool workers — one worker per process), assigned by the pool
initializer in io.dataloader.
"""

from __future__ import annotations

import threading
from typing import Any, Optional


class WorkerInfo:
    __slots__ = ("id", "num_workers", "dataset", "seed")

    def __init__(self, id: int, num_workers: int,  # noqa: A002
                 dataset: Any = None, seed: int = 0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers}, "
                f"seed={self.seed})")


_tls = threading.local()


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a DataLoader worker: that worker's WorkerInfo; in the main
    process/thread: None (reference: paddle.io.get_worker_info)."""
    return getattr(_tls, "info", None)


def _set_worker_info(info: Optional[WorkerInfo]) -> None:
    _tls.info = info
