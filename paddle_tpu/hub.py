"""Model hub (reference: python/paddle/hub.py: list / help / load over a
``hubconf.py`` protocol).

The reference resolves github:/gitee: sources by downloading a repo
archive; this environment has zero network egress, so remote sources
raise a clear error and local directories (source="local") are fully
supported — the same hubconf.py contract: entrypoints are the public
callables in the repo's hubconf.py, and ``dependencies`` is honored.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    deps = getattr(mod, "dependencies", [])
    missing = []
    for d in deps:
        try:
            importlib.import_module(d)
        except ImportError:
            missing.append(d)
    if missing:
        raise RuntimeError(f"hub repo requires missing packages: {missing}")
    return mod


def _resolve(repo_dir: str, source: str) -> str:
    if source == "local":
        return repo_dir
    raise RuntimeError(
        f"hub source {source!r} needs network access, which this "
        "environment does not have; clone the repo and use "
        "source='local'")


def list(repo_dir: str, source: str = "github",  # noqa: A001
         force_reload: bool = False) -> List[str]:
    """reference: paddle.hub.list — entrypoint names in hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",  # noqa: A001
         force_reload: bool = False) -> str:
    """reference: paddle.hub.help — the entrypoint's docstring."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"no entrypoint {model!r} in hubconf")
    return fn.__doc__ or ""


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """reference: paddle.hub.load — call the entrypoint."""
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise ValueError(f"no entrypoint {model!r} in hubconf")
    return fn(**kwargs)
