"""Text datasets + utilities (reference parity: python/paddle/text/ —
Imdb/WMT-style datasets + a simple vocab/tokenizer; zero-egress builds use
local files or deterministic synthetic corpora)."""

from __future__ import annotations

import os
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from ..io.dataset import Dataset


class Vocab:
    def __init__(self, counter: Counter, max_size: Optional[int] = None,
                 min_freq: int = 1,
                 specials=("<pad>", "<unk>", "<bos>", "<eos>")):
        self.itos: List[str] = list(specials)
        for tok, freq in counter.most_common(max_size):
            if freq < min_freq:
                break
            if tok not in self.itos:
                self.itos.append(tok)
        self.stoi: Dict[str, int] = {t: i for i, t in enumerate(self.itos)}
        self.pad_id = 0
        self.unk_id = 1
        self.bos_id = 2
        self.eos_id = 3

    def __len__(self):
        return len(self.itos)

    def encode(self, tokens: List[str]) -> List[int]:
        return [self.stoi.get(t, self.unk_id) for t in tokens]

    def decode(self, ids: List[int]) -> List[str]:
        return [self.itos[i] if 0 <= i < len(self.itos) else "<unk>"
                for i in ids]

    @classmethod
    def build_from_texts(cls, texts, tokenizer=None, **kw):
        tokenizer = tokenizer or (lambda s: s.lower().split())
        counter = Counter()
        for t in texts:
            counter.update(tokenizer(t))
        return cls(counter, **kw)


_SYNTH_POS = ["great wonderful amazing film loved it",
              "brilliant acting and a moving story",
              "best movie of the year truly superb"]
_SYNTH_NEG = ["terrible boring waste of time",
              "awful script and wooden acting",
              "worst film i have ever seen"]


class Imdb(Dataset):
    """Sentiment dataset (reference: paddle.text.Imdb). Reads an
    aclImdb-layout directory when given, else a deterministic synthetic
    corpus with the same interface."""

    def __init__(self, data_dir: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, seq_len: int = 32,
                 synthetic_size: int = 200):
        texts, labels = [], []
        if data_dir and os.path.isdir(data_dir):
            for label, sub in ((1, "pos"), (0, "neg")):
                droot = os.path.join(data_dir, mode, sub)
                for fn in sorted(os.listdir(droot)):
                    with open(os.path.join(droot, fn),
                              encoding="utf-8") as f:
                        texts.append(f.read())
                    labels.append(label)
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            for i in range(synthetic_size):
                if i % 2 == 0:
                    base = _SYNTH_POS[int(rng.integers(len(_SYNTH_POS)))]
                    labels.append(1)
                else:
                    base = _SYNTH_NEG[int(rng.integers(len(_SYNTH_NEG)))]
                    labels.append(0)
                texts.append(base)
        self.vocab = Vocab.build_from_texts(texts)
        self.seq_len = seq_len
        self.samples = []
        for t, l in zip(texts, labels):
            ids = self.vocab.encode(t.lower().split())[:seq_len]
            ids = ids + [self.vocab.pad_id] * (seq_len - len(ids))
            self.samples.append((np.asarray(ids, np.int64), np.int64(l)))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class SyntheticLMDataset(Dataset):
    """Token-stream LM dataset for GPT training/benchmarks (markov-ish
    synthetic stream so models can actually reduce loss)."""

    def __init__(self, vocab_size: int = 1024, seq_len: int = 128,
                 size: int = 512, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.size = size
        # deterministic transition table gives learnable structure
        self._next = rng.integers(0, vocab_size, vocab_size)
        self._seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed + idx)
        seq = np.empty(self.seq_len + 1, np.int64)
        seq[0] = rng.integers(self.vocab_size)
        for i in range(1, self.seq_len + 1):
            if rng.random() < 0.8:
                seq[i] = self._next[seq[i - 1]]
            else:
                seq[i] = rng.integers(self.vocab_size)
        return seq[:-1], seq[1:]

    def __len__(self):
        return self.size


def viterbi_decode(potentials, transitions):
    """Sequence-tagging decode (reference: paddle.text.viterbi_decode).
    potentials: [B, T, N]; transitions: [N, N]. Returns (scores, paths)."""
    import jax
    import jax.numpy as jnp

    pot = jnp.asarray(potentials)
    trans = jnp.asarray(transitions)
    b, t, n = pot.shape

    def step(carry, emit):
        score = carry  # [B, N]
        cand = score[:, :, None] + trans[None] + emit[:, None, :]
        best = jnp.max(cand, axis=1)
        back = jnp.argmax(cand, axis=1)
        return best, back

    init = pot[:, 0]
    scores, backs = jax.lax.scan(step, init,
                                 jnp.moveaxis(pot[:, 1:], 1, 0))
    final_scores = jnp.max(scores, axis=-1)
    last = jnp.argmax(scores, axis=-1)

    def backtrack(carry, back):
        idx = carry
        prev = jnp.take_along_axis(back, idx[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last, backs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1),
                             last[:, None]], axis=1)
    return final_scores, paths


class UCIHousing(Dataset):
    """Boston housing regression (reference: paddle.text.UCIHousing,
    text/datasets/uci_housing.py: 13 features -> price). Reads the
    whitespace-separated housing.data file when given; else a
    deterministic synthetic linear-model corpus."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 download: bool = True, synthetic_size: int = 404):
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file, dtype=np.float32)
            feats, prices = raw[:, :-1], raw[:, -1:]
            # reference normalizes features by train-split statistics
            mx, mn = feats.max(0), feats.min(0)
            feats = (feats - feats.mean(0)) / np.maximum(mx - mn, 1e-6)
            split = int(len(raw) * 0.8)
            if mode == "train":
                feats, prices = feats[:split], prices[:split]
            else:
                feats, prices = feats[split:], prices[split:]
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            feats = rng.standard_normal(
                (synthetic_size, self.FEATURE_DIM)).astype(np.float32)
            w = np.linspace(-1.0, 1.0, self.FEATURE_DIM, dtype=np.float32)
            prices = (feats @ w[:, None] + 22.5 +
                      0.1 * rng.standard_normal((synthetic_size, 1))
                      ).astype(np.float32)
        self.samples = [(feats[i], prices[i]) for i in range(len(feats))]

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Imikolov(Dataset):
    """PTB n-gram/sequence dataset (reference: paddle.text.Imikolov,
    text/datasets/imikolov.py). data_type='NGRAM' yields window_size word
    ids; 'SEQ' yields (src, trg) shifted sequences. Local PTB text file
    or deterministic synthetic corpus."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 1, seq_len: int = 20,
                 synthetic_size: int = 500):
        if data_file and os.path.exists(data_file):
            with open(data_file, encoding="utf-8") as f:
                lines = [ln.strip() for ln in f if ln.strip()]
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            words = ["the", "of", "market", "stock", "bank", "price",
                     "trade", "rate", "dollar", "share"]
            lines = [" ".join(words[int(j)] for j in
                              rng.integers(0, len(words), 12))
                     for _ in range(synthetic_size)]
        self.vocab = Vocab.build_from_texts(lines, min_freq=min_word_freq)
        self.samples = []
        for ln in lines:
            ids = self.vocab.encode(ln.lower().split())
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.samples.append(tuple(
                        np.int64(t) for t in ids[i:i + window_size]))
            else:
                seq = [self.vocab.bos_id] + ids[:seq_len] + \
                    [self.vocab.eos_id]
                src = np.asarray(seq[:-1], np.int64)
                trg = np.asarray(seq[1:], np.int64)
                self.samples.append((src, trg))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens-1M rating dataset (reference: paddle.text.Movielens,
    text/datasets/movielens.py): samples are (user_id, gender, age, job,
    movie_id, category_ids, title_ids, rating). Reads the ml-1m directory
    when given; else deterministic synthetic interactions."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0,
                 synthetic_size: int = 600):
        rng = np.random.default_rng(rand_seed)
        if data_file and os.path.isdir(data_file):
            ratings = os.path.join(data_file, "ratings.dat")
            rows = []
            with open(ratings, encoding="latin-1") as f:
                for ln in f:
                    u, m, r, _ = ln.strip().split("::")
                    rows.append((int(u), int(m), float(r)))
        else:
            rows = [(int(rng.integers(1, 500)), int(rng.integers(1, 300)),
                     float(rng.integers(1, 6)))
                    for _ in range(synthetic_size)]
        self.samples = []
        for u, m, r in rows:
            is_test = rng.random() < test_ratio
            if (mode == "test") != is_test:
                continue
            gender = np.int64(u % 2)
            age = np.int64(u % 7)
            job = np.int64(u % 21)
            cats = np.asarray([m % 18, (m * 7) % 18], np.int64)
            title = np.asarray([(m * 13 + k) % 5000 for k in range(4)],
                               np.int64)
            self.samples.append((np.int64(u), gender, age, job,
                                 np.int64(m), cats, title,
                                 np.float32(r)))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


def _expand_srl_column(col):
    """One predicate column of a CoNLL-05 props file -> B/I/O tags.
    Tokens are '*' (continue), '*)' (close), '(TAG*' (open), '(TAG*)'
    (single-token span). Reference semantics: conll05.py:200-222."""
    out, cur, inside = [], None, False
    for tok in col:
        if tok == "*":
            out.append("I-" + cur if inside else "O")
        elif tok == "*)":
            out.append("I-" + cur)
            inside = False
        elif "(" in tok and "*" in tok:
            cur = tok[1:tok.index("*")]
            out.append("B-" + cur)
            inside = ")" not in tok
        else:
            raise ValueError(f"unexpected props token: {tok!r}")
    return out


def _parse_conll05_tar(data_file):
    """The official conll05st-release tar: words/*.words.gz (one token
    per line, blank line ends a sentence) zipped against
    props/*.props.gz (column 0 = verb lemma or '-', one tag column per
    predicate). Yields (words, predicate_lemma, bio_labels) per
    (sentence, predicate) pair — reference: conll05.py:172-235."""
    import gzip
    import tarfile

    with tarfile.open(data_file) as tf:
        def key_of(name, suffix):
            # shared section key: basename minus the member suffix (e.g.
            # "test.wsj" from "words/test.wsj/test.wsj.words.gz") — name
            # order alone could zip mismatched sections if the tar
            # carries extra or renamed members
            return name.rsplit("/", 1)[-1][:-len(suffix)]

        words_by = {key_of(n, ".words.gz"): n for n in tf.getnames()
                    if n.endswith(".words.gz")}
        props_by = {key_of(n, ".props.gz"): n for n in tf.getnames()
                    if n.endswith(".props.gz")}
        if not words_by or set(words_by) != set(props_by):
            raise ValueError(
                f"{data_file} needs matching words.gz/props.gz members "
                f"(words sections {sorted(words_by)}, props sections "
                f"{sorted(props_by)})")
        word_lines, prop_lines = [], []
        # every section (e.g. test.wsj AND test.brown), paired by key
        for sec in sorted(words_by):
            wn, pn = words_by[sec], props_by[sec]
            with gzip.GzipFile(fileobj=tf.extractfile(wn)) as wf:
                word_lines += [l.decode().strip() for l in wf]
                word_lines.append("")  # section boundary = sentence end
            with gzip.GzipFile(fileobj=tf.extractfile(pn)) as pf:
                prop_lines += [l.decode().strip().split() for l in pf]
                prop_lines.append([])

    samples = []
    words, rows = [], []

    def flush():
        if words:
            lemmas = [r[0] for r in rows if r[0] != "-"]
            n_pred = len(rows[0]) - 1 if rows else 0
            for i in range(n_pred):
                col = [r[i + 1] for r in rows]
                samples.append((words[:], lemmas[i],
                                _expand_srl_column(col)))

    for word, row in zip(word_lines, prop_lines):
        if not word and not row:  # sentence boundary
            flush()
            words, rows = [], []
        else:
            words.append(word)
            rows.append(row)
    flush()  # archives without a trailing blank line
    return samples


class Conll05st(Dataset):
    """CoNLL-2005 semantic role labeling (reference: paddle.text.Conll05st,
    text/datasets/conll05.py): samples are (word_ids[T], predicate_id,
    mark[T], label_ids[T]) at fixed seq_len (TPU static shapes; the
    reference returns ragged context arrays). Given the official release
    tar via ``data_file`` (+ optional word/verb/target dict files, one
    entry per line) it parses the real words/props format; otherwise it
    serves deterministic synthetic sentences. ``mark`` flags the
    reference's 5-token predicate context window (conll05.py:246-276).
    In real-archive mode ``mode`` is ignored — like the reference, whose
    Conll05st serves only the public test sections (conll05.py:65-67) —
    and out-of-vocabulary words map to id 0, the reference's UNK_IDX
    convention (conll05.py:52: the released dicts put UNK at row 0)."""

    NUM_LABELS = 9

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 seq_len: int = 16, synthetic_size: int = 200,
                 word_dict_file: Optional[str] = None,
                 verb_dict_file: Optional[str] = None,
                 target_dict_file: Optional[str] = None):
        rng = np.random.default_rng(0 if mode == "train" else 1)
        self.seq_len = seq_len
        self.word_dict = self.predicate_dict = self.label_dict = None
        if data_file and os.path.exists(data_file):
            raw = _parse_conll05_tar(data_file)
            self.word_dict = self._load_or_build_dict(
                word_dict_file, sorted({w for ws, _, _ in raw
                                        for w in ws}))
            self.predicate_dict = self._load_or_build_dict(
                verb_dict_file, sorted({p for _, p, _ in raw}))
            self.label_dict = self._build_label_dict(
                target_dict_file, raw)
            self.samples = [self._encode(*s) for s in raw]
            return
        self.samples = []
        for _ in range(synthetic_size):
            t = int(rng.integers(5, seq_len + 1))
            words = rng.integers(4, 200, t)
            pred = int(rng.integers(0, t))
            mark = np.zeros(seq_len, np.int64)
            mark[pred] = 1
            wid = np.zeros(seq_len, np.int64)
            wid[:t] = words
            labels = np.zeros(seq_len, np.int64)
            labels[:t] = rng.integers(0, self.NUM_LABELS, t)
            self.samples.append((wid, np.int64(pred), mark, labels))

    @staticmethod
    def _load_or_build_dict(path, fallback_entries):
        if path and os.path.exists(path):
            with open(path) as f:
                return {ln.strip(): i for i, ln in enumerate(f)
                        if ln.strip()}
        return {w: i for i, w in enumerate(fallback_entries)}

    @staticmethod
    def _build_label_dict(path, raw):
        """B-X/I-X pairs for every tag, then 'O' last (reference:
        conll05.py:146-163 load_label_dict)."""
        if path and os.path.exists(path):
            with open(path) as f:
                tags = sorted({ln.strip()[2:] for ln in f
                               if ln.strip()[:2] in ("B-", "I-")})
        else:
            tags = sorted({lb[2:] for _, _, lbs in raw
                           for lb in lbs if lb != "O"})
        d = {}
        for t in tags:
            d["B-" + t] = len(d)
            d["I-" + t] = len(d)
        d["O"] = len(d)
        return d

    def _encode(self, words, predicate, labels):
        T = self.seq_len
        unk = 0
        wid = np.zeros(T, np.int64)
        lid = np.full(T, self.label_dict["O"], np.int64)
        mark = np.zeros(T, np.int64)
        n = min(len(words), T)
        wid[:n] = [self.word_dict.get(w, unk) for w in words[:n]]
        lid[:n] = [self.label_dict[lb] for lb in labels[:n]]
        v = labels.index("B-V")
        for k in range(max(0, v - 2), min(len(labels), v + 3)):
            if k < T:
                mark[k] = 1
        pred = np.int64(self.predicate_dict.get(predicate, 0))
        return wid, pred, mark, lid

    def get_dict(self):
        """(word_dict, predicate_dict, label_dict) — real-archive mode
        only (reference: conll05.py get_dict)."""
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(Dataset):
    """WMT'14 en-fr translation (reference: paddle.text.WMT14,
    text/datasets/wmt14.py): samples are (src_ids, trg_ids,
    trg_ids_next). Local parallel corpus (tab-separated src\\ttrg lines)
    or deterministic synthetic pairs."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = 1000, seq_len: int = 16,
                 synthetic_size: int = 300):
        self.dict_size = dict_size
        rng = np.random.default_rng(
            (14 if mode == "train" else 15))
        pairs = []
        if data_file and os.path.exists(data_file):
            with open(data_file, encoding="utf-8") as f:
                for ln in f:
                    if "\t" in ln:
                        s, t = ln.rstrip("\n").split("\t")[:2]
                        pairs.append((s.split(), t.split()))
            texts = [" ".join(s) + " " + " ".join(t) for s, t in pairs]
            self.vocab = Vocab.build_from_texts(texts,
                                                max_size=dict_size)
            # the default vocab tokenizer lowercases; match it here
            enc = lambda toks: self.vocab.encode(
                [t.lower() for t in toks])  # noqa: E731
        else:
            self.vocab = None
            for _ in range(synthetic_size):
                t = int(rng.integers(4, seq_len))
                src = rng.integers(4, dict_size, t)
                trg = (src[::-1] % dict_size)  # learnable mapping
                pairs.append((src, trg))
            enc = None
        self.samples = []
        bos, eos = 2, 3
        for s, t in pairs:
            sid = np.asarray(enc(s) if enc else s, np.int64)[:seq_len]
            tid = np.asarray(enc(t) if enc else t, np.int64)[:seq_len - 1]
            trg_in = np.concatenate([[bos], tid]).astype(np.int64)
            trg_next = np.concatenate([tid, [eos]]).astype(np.int64)
            self.samples.append((sid, trg_in, trg_next))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT16(WMT14):
    """WMT'16 en-de translation (reference: paddle.text.WMT16,
    text/datasets/wmt16.py) — same sample contract as WMT14."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 src_dict_size: int = 1000, trg_dict_size: int = 1000,
                 src_lang: str = "en", seq_len: int = 16,
                 synthetic_size: int = 300):
        super().__init__(data_file, mode,
                         max(src_dict_size, trg_dict_size), seq_len,
                         synthetic_size)
