"""Text datasets + utilities (reference parity: python/paddle/text/ —
Imdb/WMT-style datasets + a simple vocab/tokenizer; zero-egress builds use
local files or deterministic synthetic corpora)."""

from __future__ import annotations

import os
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from ..io.dataset import Dataset


class Vocab:
    def __init__(self, counter: Counter, max_size: Optional[int] = None,
                 min_freq: int = 1,
                 specials=("<pad>", "<unk>", "<bos>", "<eos>")):
        self.itos: List[str] = list(specials)
        for tok, freq in counter.most_common(max_size):
            if freq < min_freq:
                break
            if tok not in self.itos:
                self.itos.append(tok)
        self.stoi: Dict[str, int] = {t: i for i, t in enumerate(self.itos)}
        self.pad_id = 0
        self.unk_id = 1
        self.bos_id = 2
        self.eos_id = 3

    def __len__(self):
        return len(self.itos)

    def encode(self, tokens: List[str]) -> List[int]:
        return [self.stoi.get(t, self.unk_id) for t in tokens]

    def decode(self, ids: List[int]) -> List[str]:
        return [self.itos[i] if 0 <= i < len(self.itos) else "<unk>"
                for i in ids]

    @classmethod
    def build_from_texts(cls, texts, tokenizer=None, **kw):
        tokenizer = tokenizer or (lambda s: s.lower().split())
        counter = Counter()
        for t in texts:
            counter.update(tokenizer(t))
        return cls(counter, **kw)


_SYNTH_POS = ["great wonderful amazing film loved it",
              "brilliant acting and a moving story",
              "best movie of the year truly superb"]
_SYNTH_NEG = ["terrible boring waste of time",
              "awful script and wooden acting",
              "worst film i have ever seen"]


class Imdb(Dataset):
    """Sentiment dataset (reference: paddle.text.Imdb). Reads an
    aclImdb-layout directory when given, else a deterministic synthetic
    corpus with the same interface."""

    def __init__(self, data_dir: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, seq_len: int = 32,
                 synthetic_size: int = 200):
        texts, labels = [], []
        if data_dir and os.path.isdir(data_dir):
            for label, sub in ((1, "pos"), (0, "neg")):
                droot = os.path.join(data_dir, mode, sub)
                for fn in sorted(os.listdir(droot)):
                    with open(os.path.join(droot, fn),
                              encoding="utf-8") as f:
                        texts.append(f.read())
                    labels.append(label)
        else:
            rng = np.random.default_rng(0 if mode == "train" else 1)
            for i in range(synthetic_size):
                if i % 2 == 0:
                    base = _SYNTH_POS[int(rng.integers(len(_SYNTH_POS)))]
                    labels.append(1)
                else:
                    base = _SYNTH_NEG[int(rng.integers(len(_SYNTH_NEG)))]
                    labels.append(0)
                texts.append(base)
        self.vocab = Vocab.build_from_texts(texts)
        self.seq_len = seq_len
        self.samples = []
        for t, l in zip(texts, labels):
            ids = self.vocab.encode(t.lower().split())[:seq_len]
            ids = ids + [self.vocab.pad_id] * (seq_len - len(ids))
            self.samples.append((np.asarray(ids, np.int64), np.int64(l)))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class SyntheticLMDataset(Dataset):
    """Token-stream LM dataset for GPT training/benchmarks (markov-ish
    synthetic stream so models can actually reduce loss)."""

    def __init__(self, vocab_size: int = 1024, seq_len: int = 128,
                 size: int = 512, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.size = size
        # deterministic transition table gives learnable structure
        self._next = rng.integers(0, vocab_size, vocab_size)
        self._seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed + idx)
        seq = np.empty(self.seq_len + 1, np.int64)
        seq[0] = rng.integers(self.vocab_size)
        for i in range(1, self.seq_len + 1):
            if rng.random() < 0.8:
                seq[i] = self._next[seq[i - 1]]
            else:
                seq[i] = rng.integers(self.vocab_size)
        return seq[:-1], seq[1:]

    def __len__(self):
        return self.size


def viterbi_decode(potentials, transitions):
    """Sequence-tagging decode (reference: paddle.text.viterbi_decode).
    potentials: [B, T, N]; transitions: [N, N]. Returns (scores, paths)."""
    import jax
    import jax.numpy as jnp

    pot = jnp.asarray(potentials)
    trans = jnp.asarray(transitions)
    b, t, n = pot.shape

    def step(carry, emit):
        score = carry  # [B, N]
        cand = score[:, :, None] + trans[None] + emit[:, None, :]
        best = jnp.max(cand, axis=1)
        back = jnp.argmax(cand, axis=1)
        return best, back

    init = pot[:, 0]
    scores, backs = jax.lax.scan(step, init,
                                 jnp.moveaxis(pot[:, 1:], 1, 0))
    final_scores = jnp.max(scores, axis=-1)
    last = jnp.argmax(scores, axis=-1)

    def backtrack(carry, back):
        idx = carry
        prev = jnp.take_along_axis(back, idx[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(backtrack, last, backs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1),
                             last[:, None]], axis=1)
    return final_scores, paths
