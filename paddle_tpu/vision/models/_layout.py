"""Shared NHWC-internal / NCHW-public boundary helpers for the vision
zoo (ResNet/MobileNet/VGG data_format="NHWC"): the network runs
channel-last (the TPU-fast layout) and transposes once at each model
boundary so the public contract stays NCHW."""

from ... import dispatch


def boundary_in(x, data_format):
    if data_format == "NHWC":
        return dispatch.wrapped_ops["transpose"](x, [0, 2, 3, 1])
    return x


def boundary_out(x, data_format):
    if data_format == "NHWC":
        return dispatch.wrapped_ops["transpose"](x, [0, 3, 1, 2])
    return x


def flatten_nchw_order(x, data_format, spatial_is_1x1):
    """Flatten to [N, C*H*W] in the NCHW order the classifier weights
    expect; a 1x1 spatial map flattens identically in both layouts."""
    if data_format == "NHWC" and not spatial_is_1x1:
        x = dispatch.wrapped_ops["transpose"](x, [0, 3, 1, 2])
    return dispatch.wrapped_ops["flatten"](x, 1)
