"""ResNet family (reference: python/paddle/vision/models/resnet.py —
resnet18/34/50/101/152 with BasicBlock/BottleneckBlock).

``data_format="NHWC"`` runs the whole network channel-last — the fast
layout on TPU (the MXU consumes NHWC convs without the per-conv
transposes XLA inserts around NCHW) — while the public input/output
contract stays NCHW: the input is transposed once at the model boundary.
"""

from __future__ import annotations

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(planes, data_format=data_format)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1,
                               bias_attr=False, data_format=data_format)
        self.bn2 = norm_layer(planes, data_format=data_format)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=data_format)
        self.bn1 = norm_layer(width, data_format=data_format)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(width, data_format=data_format)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=data_format)
        self.bn3 = norm_layer(planes * self.expansion,
                              data_format=data_format)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        if not self.training and self._try_fused_eval_gate(x):
            return self._fused_eval(x)
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)

    def _try_fused_eval_gate(self, x) -> bool:
        """Eval-only fused-block path (the conv_fusion_op kernel class):
        one Pallas launch per block keeps the whole conv+BN+relu chain's
        intermediates in VMEM — see ops/pallas/fused_conv_block.py."""
        try:
            from ...ops.pallas.fused_conv_block import (
                fused_bottleneck_supported)
            shape = tuple(x.shape)
            return len(shape) == 4 and fused_bottleneck_supported(
                self, shape, self._block_data_format())
        except Exception:
            return False

    def _block_data_format(self) -> str:
        return getattr(self.conv1, "_data_format", "NCHW")

    def _fused_eval(self, x):
        from ... import dispatch
        from ...ops.pallas.fused_conv_block import (fused_bottleneck_eval,
                                                    pack_bottleneck)
        # fold/pack once per weight version (eval weights are frozen; a
        # training step or set_state_dict in between swaps the array
        # objects and invalidates the key). The key holds the arrays
        # THEMSELVES and compares by identity: keeping them alive means
        # CPython can never reallocate a new array at a freed array's
        # address, which an id()-tuple key was vulnerable to (stale pack
        # served after a weight reload).
        key = (self.conv1.weight.value, self.conv2.weight.value,
               self.conv3.weight.value, self.bn1._mean.value)
        cached = getattr(self, "_fused_pack", None)
        if cached is None or len(cached[0]) != len(key) or \
                any(a is not b for a, b in zip(cached[0], key)):
            self._fused_pack = (key, pack_bottleneck(self))
        params = self._fused_pack[1]

        def run(xv, *p):
            return fused_bottleneck_eval(xv, *p)

        return dispatch.call_fn(run, "fused_bottleneck_eval", True,
                                (x, *params), {})


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        self.data_format = data_format
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = nn.BatchNorm2D(self.inplanes, data_format=data_format)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False,
                          data_format=self.data_format),
                nn.BatchNorm2D(planes * block.expansion,
                               data_format=self.data_format))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width,
                        data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                data_format=self.data_format))
        return nn.Sequential(*layers)

    def forward(self, x):
        from ._layout import boundary_in, boundary_out, flatten_nchw_order
        x = boundary_in(x, self.data_format)
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = flatten_nchw_order(x, self.data_format, self.with_pool)
            x = self.fc(x)
        else:
            x = boundary_out(x, self.data_format)
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=128, **kwargs)
