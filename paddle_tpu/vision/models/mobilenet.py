"""MobileNet v1/v2 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py).

``data_format="NHWC"`` runs the network channel-last (the TPU-fast
layout, like ResNet's) while the public input/output contract stays
NCHW: one transpose at each model boundary.
"""

from ... import nn
from ._layout import (boundary_in as _nchw_boundary_in,
                      boundary_out as _nchw_boundary_out)
from ._layout import flatten_nchw_order


class _ConvBNRelu(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 relu6=False, data_format="NCHW"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False,
                              data_format=data_format)
        self.bn = nn.BatchNorm2D(out_c, data_format=data_format)
        self.act = nn.ReLU6() if relu6 else nn.ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _DepthwiseSep(nn.Layer):
    def __init__(self, in_c, out_c, stride, data_format="NCHW"):
        super().__init__()
        self.dw = _ConvBNRelu(in_c, in_c, 3, stride, 1, groups=in_c,
                              data_format=data_format)
        self.pw = _ConvBNRelu(in_c, out_c, 1, data_format=data_format)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = data_format

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
            [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNRelu(3, c(32), 3, 2, 1, data_format=data_format)]
        for in_c, out_c, s in cfg:
            layers.append(_DepthwiseSep(c(in_c), c(out_c), s,
                                        data_format=data_format))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = _nchw_boundary_in(x, self.data_format)
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ... import dispatch
            x = flatten_nchw_order(x, self.data_format, self.with_pool)
            x = self.fc(x)
        else:
            x = _nchw_boundary_out(x, self.data_format)
        return x


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio,
                 data_format="NCHW"):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNRelu(in_c, hidden, 1, relu6=True,
                                      data_format=data_format))
        layers.append(_ConvBNRelu(hidden, hidden, 3, stride, 1,
                                  groups=hidden, relu6=True,
                                  data_format=data_format))
        layers.append(nn.Conv2D(hidden, out_c, 1, bias_attr=False,
                                data_format=data_format))
        layers.append(nn.BatchNorm2D(out_c, data_format=data_format))
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = data_format
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]

        def c(ch):
            return max(int(ch * scale), 8)

        in_c = c(32)
        layers = [_ConvBNRelu(3, in_c, 3, 2, 1, relu6=True,
                              data_format=data_format)]
        for t, ch, n, s in cfg:
            out_c = c(ch)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c,
                                                s if i == 0 else 1, t,
                                                data_format=data_format))
                in_c = out_c
        self.last_c = c(1280) if scale > 1.0 else 1280
        layers.append(_ConvBNRelu(in_c, self.last_c, 1, relu6=True,
                                  data_format=data_format))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1, data_format=data_format)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(self.last_c,
                                                      num_classes))

    def forward(self, x):
        x = _nchw_boundary_in(x, self.data_format)
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten_nchw_order(x, self.data_format, self.with_pool)
            x = self.classifier(x)
        else:
            x = _nchw_boundary_out(x, self.data_format)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
