"""VGG (reference: python/paddle/vision/models/vgg.py)."""

from ... import nn

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512,
          512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_features(cfg, batch_norm=False, data_format="NCHW"):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2, data_format=data_format))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1,
                                    data_format=data_format))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v, data_format=data_format))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        self.num_classes = num_classes
        self.data_format = data_format
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7),
                                                data_format=data_format)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        from ._layout import boundary_in, boundary_out, flatten_nchw_order
        x = boundary_in(x, self.data_format)
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            # the 7x7 pooled map is NOT 1x1: flatten in NCHW order
            x = flatten_nchw_order(x, self.data_format, False)
            x = self.classifier(x)
        else:
            x = boundary_out(x, self.data_format)
        return x


def _vgg(cfg_key, batch_norm, **kwargs):
    fmt = kwargs.get("data_format", "NCHW")
    return VGG(_make_features(_CFGS[cfg_key], batch_norm, fmt), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, **kwargs)
