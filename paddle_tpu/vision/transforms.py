"""Image transforms (reference: python/paddle/vision/transforms/ —
numpy-array implementations of the torchvision-style transform set)."""

from __future__ import annotations

import numbers
import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 -> CHW float32/255 (no-op on already-CHW float)."""

    def __call__(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[None]
        elif img.ndim == 3 and img.shape[-1] in (1, 3, 4) and \
                img.shape[0] not in (1, 3, 4):
            img = np.transpose(img, (2, 0, 1))
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        return img.astype(np.float32)


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        shape = list(img.shape)
        shape[h_ax], shape[w_ax] = self.size
        return np.asarray(jax.image.resize(jnp.asarray(img), shape,
                                           method="linear"))


class CenterCrop:
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        i = max((img.shape[h_ax] - th) // 2, 0)
        j = max((img.shape[w_ax] - tw) // 2, 0)
        sl = [slice(None)] * img.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return img[tuple(sl)]


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            pad = [(0, 0)] * img.ndim
            pad[h_ax] = (self.padding, self.padding)
            pad[w_ax] = (self.padding, self.padding)
            img = np.pad(img, pad, mode="constant")
        th, tw = self.size
        i = np.random.randint(0, img.shape[h_ax] - th + 1)
        j = np.random.randint(0, img.shape[w_ax] - tw + 1)
        sl = [slice(None)] * img.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return img[tuple(sl)]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        img = np.asarray(img)
        if np.random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return np.flip(img, axis=2 if chw else 1).copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        img = np.asarray(img)
        if np.random.random() < self.prob:
            chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
            return np.flip(img, axis=1 if chw else 0).copy()
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)
